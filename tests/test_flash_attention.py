"""Flash attention: numerics vs dense (values AND grads), shard_map routing.

The kernel runs in Pallas interpret mode on CPU (same semantics as the
Mosaic build on TPU). The sharding tests compile under the 8-device sim and
assert GSPMD never all-gathers the kernel inputs — the failure mode
parallel.auto_shard exists to prevent.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import distributed_tpu as dtpu
from distributed_tpu.ops.flash_attention import flash_attention


def dense_attention(q, k, v, causal):
    b, t, h, d = q.shape
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) / np.sqrt(d)
    if causal:
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    a = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", a, v)


def _qkv(shape, dtype=jnp.float32, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.standard_normal(shape), dtype) for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("shape", [(2, 64, 2, 16), (1, 100, 3, 32)])
def test_matches_dense_values_and_grads(shape, causal):
    q, k, v = _qkv(shape)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
            * v
        )

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal) * v)

    out = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
    ref = dense_attention(q, k, v, causal)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=1e-5)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(a, b, atol=5e-5, rtol=1e-4)


def test_ragged_seq_and_uneven_blocks():
    # T=257: padding rows/cols must not leak into real outputs.
    q, k, v = _qkv((1, 257, 2, 64))
    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
    ref = dense_attention(q, k, v, True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=1e-5)


def test_incompatible_blocks_are_repaired():
    """Mismatched block sizes are clamped to a compatible pair instead of
    silently dropping trailing rows (the grid must cover all of T)."""
    q, k, v = _qkv((1, 256, 1, 16))
    out = flash_attention(q, k, v, causal=True, block_q=96, block_k=128)
    ref = dense_attention(q, k, v, True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=1e-5)


def test_default_blocks_midsize_sequences():
    """Default block sizes on 512 <= T < 1024 (where flash='auto' kicks in):
    block_k is clamped to the q-rounded length so padded work stays within
    one q-block, and padding must not leak into outputs."""
    for t in (513, 600):
        q, k, v = _qkv((1, t, 1, 32))
        out = flash_attention(q, k, v, causal=True)  # default blocks
        ref = dense_attention(q, k, v, True)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=1e-5)


@pytest.mark.smoke
def test_bf16_inputs():
    q, k, v = _qkv((2, 128, 2, 32), jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
    ref = dense_attention(q, k, v, True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        out.astype(np.float32), ref.astype(np.float32), atol=3e-2
    )


def test_no_allgather_under_dp_mesh(devices):
    """shard_rows must keep the kernel per-shard: compiling under a
    'data'-sharded batch may not introduce an all-gather of q/k/v."""
    strategy = dtpu.DataParallel()
    b, t, h, d = 16, 64, 2, 32
    q, k, v = _qkv((b, t, h, d))
    batch = strategy.put_batch({"x": np.asarray(q)})
    qs = batch["x"]

    from jax.sharding import PartitionSpec as P

    from distributed_tpu.parallel.auto_shard import shard_rows

    def call(q, k, v):
        with strategy.scope():
            spec = P("data", None, None, None)
            return shard_rows(
                lambda a, b2, c: flash_attention(
                    a, b2, c, causal=True, block_q=32, block_k=32
                ),
                (q, k, v), (spec, spec, spec), spec,
            )

    f = jax.jit(call)
    hlo = f.lower(qs, k, v).compile().as_text()
    assert "all-gather" not in hlo
    out = f(qs, k, v)
    ref = dense_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5, rtol=1e-5)


def test_mha_flash_equals_dense_model_level(devices):
    """A transformer LM with flash=True in every MHA must match the dense
    attention model's loss exactly enough for training parity."""
    import distributed_tpu.nn as nn

    def make(flash):
        return nn.Sequential([
            nn.Embedding(64, 32),
            nn.MultiHeadAttention(4, causal=True, flash=flash),
            nn.Dense(64),
        ])

    x = np.asarray(
        np.random.default_rng(0).integers(0, 64, (8, 96)), np.int32
    )
    ma, mb = make(True), make(False)
    pa, sa, _ = ma.init(jax.random.PRNGKey(0), (96,))
    logits_a, _ = ma.apply(pa, {}, x)
    logits_b, _ = mb.apply(pa, {}, x)  # identical params
    np.testing.assert_allclose(logits_a, logits_b, atol=2e-4, rtol=1e-4)


def test_fused_xent_sharded_no_allgather(devices):
    """The Pallas loss inside a DP step must also stay per-shard."""
    from distributed_tpu.ops.pallas_kernels import (
        pallas_sparse_categorical_crossentropy,
    )

    strategy = dtpu.DataParallel()
    n, c = 64, 32
    rng = np.random.default_rng(0)
    logits = np.asarray(rng.standard_normal((n, c)), np.float32)
    labels = np.asarray(rng.integers(0, c, (n,)), np.int32)
    batch = strategy.put_batch({"x": logits, "y": labels})

    def loss(lg, lb):
        with strategy.scope():
            return pallas_sparse_categorical_crossentropy(lg, lb)

    f = jax.jit(loss)
    hlo = f.lower(batch["x"], batch["y"]).compile().as_text()
    assert "all-gather" not in hlo
    got = float(f(batch["x"], batch["y"]))
    from distributed_tpu.ops import losses

    want = float(losses.sparse_categorical_crossentropy(logits, labels))
    assert abs(got - want) < 1e-5


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("shape", [
    (2, 64, 2, 64),    # head_dim 64: two heads per 128-lane block
    (1, 100, 1, 128),  # head_dim 128: one head per block, ragged T
    (2, 72, 4, 64),    # multiple head blocks, ragged T
])
def test_packed_layout_matches_dense_values_and_grads(shape, causal):
    """The lane-packed (B,T,H*D) kernels (head_dim 64/128 — no transposes)
    must match dense attention in values AND all three gradients."""
    from distributed_tpu.ops.flash_attention import _packed_supported

    assert _packed_supported(shape[2], shape[3])
    q, k, v = _qkv(shape, seed=3)
    out = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
    want = dense_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
        return jnp.sum(jnp.sin(o))

    def loss_dense(q, k, v):
        return jnp.sum(jnp.sin(dense_attention(q, k, v, causal)))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-4)


def test_packed_compact_stats_branch_matches(monkeypatch):
    """Long-context residual policy: above _COMPACT_STATS_MIN_T the packed
    path saves compact per-head stats and re-expands in backward — values
    and grads must be identical to the short-T (lane-replicated) branch."""
    from distributed_tpu.ops import flash_attention as fa

    q, k, v = _qkv((1, 96, 2, 64), seed=5)

    def loss(q, k, v):
        o = flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
        return jnp.sum(jnp.sin(o))

    g_fast = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    fa._packed_cached.cache_clear()  # static config changed: force retrace
    monkeypatch.setattr(fa, "_COMPACT_STATS_MIN_T", 32)
    try:
        g_compact = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    finally:
        fa._packed_cached.cache_clear()
    for a, b in zip(g_fast, g_compact):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)
