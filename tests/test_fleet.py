"""Disaggregated serving fleet: router, handoff, autoscaling, replica loss.

The decisive test is the same one the serving engine pinned, lifted one
level: every request served through the FLEET — whatever the replica
count, transfer availability, autoscaling activity, or replica kills
around it — must produce exactly the tokens a sequential per-request
``generate()`` produces. Router/autoscaler arithmetic is pure host code
and is tested from synthetic traces without touching a model.

Kept lean (tier-1 runs on a 1-core box): one tiny LM + one shared
compiled-programs fixture for the whole module; the replica-count x
fault matrix is @slow.
"""

import numpy as np
import pytest

import distributed_tpu as dtpu
from distributed_tpu.fleet import (
    EnginePrograms, HandoffIncompatible, QueueAutoscaler, Router,
    ServingFleet, install_kv, pack_kv,
)
from distributed_tpu.resilience import ElasticPolicy, FaultInjector
from distributed_tpu.serving import Request
from distributed_tpu.serving.kv_cache import PagedKVCache


@pytest.fixture(scope="module")
def lm():
    model = dtpu.Model(dtpu.models.transformer_lm(
        32, num_layers=2, d_model=16, num_heads=2, max_len=64))
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    model.build((16,))
    return model


@pytest.fixture(scope="module")
def programs(lm):
    return EnginePrograms(lm)


def _requests(seed=0, n=6, vocab=32, p_range=(2, 9), m_range=(4, 10)):
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(0, vocab, (int(t),)).astype(np.int32)
               for t in rng.integers(*p_range, n)]
    news = [int(m) for m in rng.integers(*m_range, n)]
    return prompts, news


def _sequential_generate(model, prompts, news):
    return [model.generate(p[None], m, temperature=0.0)[0]
            for p, m in zip(prompts, news)]


def _fleet(lm, programs, **kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("block_size", 4)
    kw.setdefault("max_len", 64)
    return ServingFleet(lm, programs=programs, **kw)


# ------------------------------------------------------------------ router --
def test_router_weighted_fairness_is_wfq():
    """Weight-2 tenant a gets exactly 2x tenant b's service under
    contention, by virtual-finish-time order (deterministic)."""
    r = Router(tenant_weights={"a": 2.0, "b": 1.0})
    for i in range(6):
        adm, _ = r.submit(Request(np.array([1], np.int32), 4),
                          tenant="a", now=0.0)
        assert adm.accepted
        adm, _ = r.submit(Request(np.array([1], np.int32), 4),
                          tenant="b", now=0.0)
        assert adm.accepted
    order = [r.next_request().tenant for _ in range(6)]
    assert order.count("a") == 4 and order.count("b") == 2
    # Drains completely, ending with the backlogged light tenant.
    rest = [r.next_request().tenant for _ in range(6)]
    assert r.next_request() is None
    assert (order + rest).count("a") == 6


def test_router_bounded_queue_rejects_overflow():
    r = Router(max_queue=2)
    a1, _ = r.submit(Request(np.array([1], np.int32), 2), now=0.0)
    a2, _ = r.submit(Request(np.array([1], np.int32), 2), now=0.0)
    a3, s3 = r.submit(Request(np.array([1], np.int32), 2), now=0.1)
    assert a1.accepted and a2.accepted
    assert not a3.accepted and a3.reason == "queue_full" and s3 is None
    assert r.telemetry()["rejected_by_reason"] == {"queue_full": 1}
    r.next_request()
    a4, _ = r.submit(Request(np.array([1], np.int32), 2), now=0.2)
    assert a4.accepted  # space freed


def test_router_slo_admission_uses_observed_rate():
    r = Router(slo_ttft_s=1.0)
    # Cold start: no completions, no evidence, no rejection.
    adm, _ = r.submit(Request(np.array([1], np.int32), 2), now=0.0)
    assert adm.accepted
    # Two completions 10s apart -> 0.1 req/s -> a new arrival behind a
    # 1-deep queue predicts (1+1)/0.1 = 20s >> 1s SLO: reject.
    r.observe_finish(10.0)
    r.observe_finish(20.0)
    assert r.service_rate() == pytest.approx(0.1)
    adm, _ = r.submit(Request(np.array([1], np.int32), 2), now=20.0)
    assert not adm.accepted and adm.reason == "slo"
    rej = r.rejected[-1]
    assert rej["predicted_ttft_s"] == pytest.approx(20.0)
    # Fast service admits: 50 req/s.
    fast = Router(slo_ttft_s=1.0)
    for t in (0.0, 0.02, 0.04):
        fast.observe_finish(t)
    adm, _ = fast.submit(Request(np.array([1], np.int32), 2), now=0.05)
    assert adm.accepted


def test_router_requeue_goes_to_head():
    r = Router()
    _, s1 = r.submit(Request(np.array([1], np.int32), 2), now=0.0)
    _, s2 = r.submit(Request(np.array([1], np.int32), 2), now=0.0)
    first = r.next_request()
    assert first is s1
    r.requeue([first], now=1.0)
    assert r.requeues == 1
    assert r.next_request() is s1  # original vft: ahead of s2...
    assert r.next_request() is s2


def test_router_place_breaks_affinity_ties_by_queue_depth():
    """Prefix-affinity ties are NOT broken by candidate order: the
    replica with the lowest queue depth wins (it will admit soonest),
    then least in-flight, then name — fully deterministic."""

    class Rep:
        def __init__(self, name, queue_depth, in_flight, holds=False):
            self.name = name
            self.queue_depth = queue_depth
            self.in_flight = in_flight
            self._holds = holds
            self.holds_prefix = lambda seq: self._holds

    r = Router()
    _, seq = r.submit(Request(np.array([1, 2], np.int32), 4), now=0.0)
    # Equal affinity (none warm): lowest queue depth wins even when it
    # appears LAST in the candidate list and has more in-flight.
    a = Rep("a", queue_depth=5, in_flight=0)
    b = Rep("b", queue_depth=2, in_flight=3)
    assert r.place(seq, [a, b]) is b
    assert r.place(seq, [b, a]) is b
    # Warm cache outranks any queue: affinity first.
    warm = Rep("w", queue_depth=9, in_flight=9, holds=True)
    assert r.place(seq, [a, b, warm]) is warm
    # Two equally-warm replicas: shorter queue wins the tie.
    warm2 = Rep("v", queue_depth=1, in_flight=9, holds=True)
    assert r.place(seq, [warm, warm2]) is warm2
    # Full tie everywhere: name decides, independent of order.
    c1, c2 = Rep("c1", 1, 1), Rep("c2", 1, 1)
    assert r.place(seq, [c2, c1]) is c1
    # A candidate without queue_depth falls back to in_flight.
    plain = Rep("p", 0, 2)
    del plain.queue_depth
    busy = Rep("q", 3, 3)
    assert r.place(seq, [busy, plain]) is plain


def test_router_peek_matches_next_request_without_popping():
    r = Router(tenant_weights={"a": 2.0, "b": 1.0})
    r.submit(Request(np.array([1], np.int32), 4), tenant="b", now=0.0)
    r.submit(Request(np.array([1], np.int32), 4), tenant="a", now=0.0)
    head = r.peek()
    assert head is r.peek()          # idempotent: nothing popped
    assert r.queue_depth == 2
    assert r.next_request() is head  # same WFQ order as the pop
    assert r.peek() is not head
    r.next_request()
    assert r.peek() is None


# -------------------------------------------------------------- autoscaler --
def test_autoscaler_grow_shrink_from_synthetic_trace():
    asc = QueueAutoscaler(1, 3, queue_high=2.0, queue_low=0.5,
                          cooldown_s=1.0)
    assert asc.target == 1
    # Burst: queue 8 deep on 1 replica -> grow.
    assert asc.decide(0.0, queue_depth=8, replicas=1) == 2
    # Cooldown: still hot at t=0.5 but no change.
    assert asc.decide(0.5, queue_depth=8, replicas=2) == 2
    # Past cooldown: still hot -> grow to the max, then clamp.
    assert asc.decide(1.1, queue_depth=8, replicas=2) == 3
    assert asc.decide(2.2, queue_depth=9, replicas=3) == 3  # at max
    # Drained queue + a whole replica's slots idle -> shrink (slowly).
    assert asc.decide(3.3, queue_depth=0, replicas=3, free_slots=4,
                      slots_per_replica=4) == 2
    assert asc.decide(3.4, queue_depth=0, replicas=2, free_slots=4,
                      slots_per_replica=4) == 2  # cooldown again
    assert asc.decide(4.5, queue_depth=0, replicas=2, free_slots=4,
                      slots_per_replica=4) == 1  # floor
    assert asc.decide(5.6, queue_depth=0, replicas=1, free_slots=4,
                      slots_per_replica=4) == 1
    reasons = [e["reason"] for e in asc.events]
    assert any("queue_depth" in r for r in reasons)
    assert len(asc.events) == 4  # 2 grows + 2 shrinks, each recorded


def test_autoscaler_slo_breach_grows_and_probe_seam():
    asc = QueueAutoscaler(1, 4, queue_high=100.0, queue_low=0.1,
                          slo_ttft_s=0.5, cooldown_s=0.0)
    # Queue looks fine but the tail is blown: grow on p99.
    assert asc.decide(0.0, queue_depth=1, replicas=1,
                      recent_p99_ttft=2.0) == 2
    assert "slo" in asc.events[0]["reason"]
    # The ElasticPolicy capacity seam: the SAME probe contract.
    policy = ElasticPolicy(min_workers=1, max_workers=4, probe=asc.probe)
    assert policy.probe() == 2
    assert policy.snap(policy.probe(), default_max=4) == 2
    with pytest.raises(ValueError, match="queue_low"):
        QueueAutoscaler(1, 2, queue_high=1.0, queue_low=1.0)
    with pytest.raises(ValueError, match="max_replicas"):
        QueueAutoscaler(3, 2)


# ----------------------------------------------------------------- handoff --
def test_handoff_pack_install_roundtrip_across_pools(lm):
    """KV packed from one pool installs into ANOTHER pool's (different)
    blocks and reads back identically — placement is the receiver's,
    content is position-aligned (the sharded-checkpoint discipline)."""
    import jax

    def pool():
        return PagedKVCache(lm.module, lm.params, max_slots=2,
                            block_size=4, max_blocks_per_seq=8,
                            num_blocks=17, dtype=np.float32)

    src, dst = pool(), pool()
    assert src.reserve(0, 10)  # 3 blocks
    # Fill src's blocks with recognizable data via a direct write.
    paths_leaves = jax.tree_util.tree_flatten(src.caches)
    leaves, treedef = paths_leaves
    rng = np.random.default_rng(0)
    filled = []
    for leaf in leaves:
        data = rng.normal(size=leaf.shape).astype(np.float32)
        filled.append(jax.numpy.asarray(data))
    src.caches = jax.tree_util.tree_unflatten(treedef, filled)
    payload = pack_kv(src, 0, 10)
    assert payload.cached_len == 10 and payload.nbytes > 0
    # Skew dst's free list so its granted block ids differ from src's.
    assert dst.reserve(1, 6)
    assert dst.reserve(0, 10)
    src_ids = src._slot_blocks[0][:3]
    dst_ids = dst._slot_blocks[0][:3]
    assert src_ids != dst_ids
    # 3 blocks per layer leaf (2 layers x k/v = 4 leaves).
    assert install_kv(dst, 0, payload) == 3 * len(payload.blocks)
    for s_leaf, d_leaf in zip(
            jax.tree_util.tree_leaves(src.caches),
            jax.tree_util.tree_leaves(dst.caches)):
        np.testing.assert_array_equal(
            np.asarray(s_leaf)[src_ids], np.asarray(d_leaf)[dst_ids]
        )
    # Incompatibility is loud, and pre-scatter: block-size mismatch.
    bad = pack_kv(src, 0, 10)
    bad.block_size = 8
    with pytest.raises(HandoffIncompatible, match="block_size"):
        install_kv(dst, 0, bad)
    # Dtype is gated per LEAF (an int8 pool mixes int8 q with f32 scale
    # leaves, so no single payload dtype string can stand for all of
    # them): a shipped run whose data dtype disagrees with its
    # destination leaf refuses to install.
    bad2 = pack_kv(src, 0, 10)
    key = next(iter(bad2.blocks))
    bad2.blocks[key] = bad2.blocks[key].astype(np.float64)
    with pytest.raises(HandoffIncompatible, match="dtype"):
        install_kv(dst, 0, bad2)


# -------------------------------------------------------------------- e2e --
def test_fleet_matches_sequential_generate_with_transfer(lm, programs):
    """Disaggregated serving (prefill pool -> KV handoff -> decode pool)
    is token-identical to per-request generate()."""
    prompts, news = _requests(seed=0)
    want = _sequential_generate(lm, prompts, news)
    fleet = _fleet(lm, programs, decode_replicas=2, prefill_replicas=1)
    outs = fleet.run([Request(p, m) for p, m in zip(prompts, news)])
    for i, (w, g) in enumerate(zip(want, outs)):
        np.testing.assert_array_equal(w, g, err_msg=f"request {i}")
    t = fleet.last_run_telemetry
    assert t["lost_requests"] == 0
    assert t["handoffs"]["installed"] == len(prompts)
    assert t["handoffs"]["fallback_reprefill"] == 0
    assert t["prefill_pool"]["prefills"] == len(prompts)
    # Lifecycle rows: complete and ordered for every request.
    for row in t["requests"]:
        assert row["enqueued_s"] <= row["first_token_s"] <= \
            row["finished_s"]
        assert row["replica"] is not None
    assert t["time_to_first_token"]["p99"] >= \
        t["time_to_first_token"]["p50"] > 0


def test_fleet_reprefill_fallback_when_transfer_unavailable(lm, programs):
    """transfer='none': payloads cannot travel, decode replicas re-prefill
    every context — same tokens, recompute instead of transfer."""
    prompts, news = _requests(seed=1)
    want = _sequential_generate(lm, prompts, news)
    fleet = _fleet(lm, programs, decode_replicas=2, prefill_replicas=1,
                   transfer="none")
    outs = fleet.run([Request(p, m) for p, m in zip(prompts, news)])
    for w, g in zip(want, outs):
        np.testing.assert_array_equal(w, g)
    t = fleet.last_run_telemetry
    assert t["handoffs"]["installed"] == 0
    assert t["handoffs"]["fallback_reprefill"] == len(prompts)
    assert t["lost_requests"] == 0


def test_fleet_colocated_prefill_when_no_prefill_pool(lm, programs):
    prompts, news = _requests(seed=2, n=4)
    want = _sequential_generate(lm, prompts, news)
    fleet = _fleet(lm, programs, decode_replicas=2, prefill_replicas=0)
    outs = fleet.run([Request(p, m) for p, m in zip(prompts, news)])
    for w, g in zip(want, outs):
        np.testing.assert_array_equal(w, g)
    assert fleet.last_run_telemetry["prefill_pool"]["replicas"] == 0


def test_replica_kill_requeues_and_finishes_token_exact(lm, programs,
                                                        tmp_path):
    """The tentpole fault property: a decode replica killed mid-request
    loses nothing — the router re-queues its in-flight work, survivors
    re-prefill and finish, outputs stay token-exact, and the reconcile
    loop replaces the dead replica."""
    prompts, news = _requests(seed=3, n=6, m_range=(6, 12))
    want = _sequential_generate(lm, prompts, news)
    marker = tmp_path / "fleet-fault-fired"
    fault = FaultInjector("replica_kill", replica="decode-1", at_step=2,
                          once_marker=marker)
    fleet = _fleet(lm, programs, decode_replicas=2, prefill_replicas=1,
                   fault=fault)
    outs = fleet.run([Request(p, m) for p, m in zip(prompts, news)])
    for w, g in zip(want, outs):
        np.testing.assert_array_equal(w, g)
    t = fleet.last_run_telemetry
    assert t["lost_requests"] == 0
    (kill,) = t["decode_pool"]["kills"]
    assert kill["replica"] == "decode-1" and kill["requeued"] >= 1
    assert t["router"]["requeues"] == kill["requeued"]
    assert t["handoffs"]["fallback_reprefill"] >= kill["requeued"]
    assert any(r["requeues"] > 0 for r in t["requests"])
    # Self-healing: the pool respawned a replacement after the kill.
    assert any(e["event"] == "spawn" for e in t["decode_pool"]["events"])
    assert marker.exists() and fault.fired
    # Once-marker semantics: the same spec re-armed from env does not
    # fire again while the marker stands.
    again = FaultInjector("replica_kill", replica="decode-1", at_step=2,
                          once_marker=marker)
    assert not again.should_kill_replica("decode-1", 99)


def test_fleet_autoscaler_grows_under_burst_and_drains(lm, programs):
    prompts, news = _requests(seed=4, n=8, m_range=(6, 12))
    want = _sequential_generate(lm, prompts, news)
    asc = QueueAutoscaler(1, 3, queue_high=1.5, queue_low=0.25,
                          cooldown_s=0.0, spinup_s=0.005)
    fleet = _fleet(lm, programs, decode_replicas=1, prefill_replicas=1,
                   autoscaler=asc)
    outs = fleet.run([Request(p, m) for p, m in zip(prompts, news)])
    for w, g in zip(want, outs):
        np.testing.assert_array_equal(w, g)
    t = fleet.last_run_telemetry
    assert t["lost_requests"] == 0
    grows = [e for e in t["autoscaler"]["events"] if e["to"] > e["from"]]
    assert grows, t["autoscaler"]["events"]
    spawns = [e for e in t["decode_pool"]["events"]
              if e["event"] == "spawn"]
    assert spawns and all(e["ready_at"] >= e["t"] for e in spawns)


# ------------------------------------------------------------ fault plumbing --
def test_faultinjector_replica_mode_env_and_validation(monkeypatch):
    monkeypatch.setenv("DTPU_FAULT",
                       "replica_kill:replica=decode-3,at_step=7")
    inj = FaultInjector.from_env()
    assert inj.mode == "replica_kill" and inj.replica == "decode-3"
    assert inj.at_step == 7
    # Wrong name / early step: not armed; right name at the step: once.
    assert not inj.should_kill_replica("decode-1", 10)
    assert not inj.should_kill_replica("decode-3", 3)
    assert inj.should_kill_replica("decode-3", 7)
    assert not inj.should_kill_replica("decode-3", 8)  # fired
    # Training callback path ignores the fleet-addressed mode entirely.
    inj2 = FaultInjector("replica_kill", replica="decode-0", at_step=0)
    inj2.on_batch_end(model=None, step=99, logs={})
    assert not inj2.fired
    with pytest.raises(ValueError, match="replica="):
        FaultInjector("replica_kill")


# ------------------------------------------------------------------- @slow --
@pytest.mark.slow
@pytest.mark.parametrize("replicas,transfer,at_step", [
    (2, "blocks", 1), (2, "none", 4), (3, "blocks", 4), (3, "none", 1),
])
def test_fleet_kill_matrix(lm, programs, replicas, transfer, at_step):
    """Replica-count x transfer x kill-step matrix: recovery is
    token-exact with zero lost requests everywhere."""
    prompts, news = _requests(seed=10 + replicas, n=8, m_range=(6, 14))
    want = _sequential_generate(lm, prompts, news)
    fault = FaultInjector("replica_kill", replica="decode-1",
                          at_step=at_step)
    fleet = _fleet(lm, programs, decode_replicas=replicas,
                   prefill_replicas=1, transfer=transfer, fault=fault)
    outs = fleet.run([Request(p, m) for p, m in zip(prompts, news)],
                     arrival_times=[0.001 * i for i in range(len(news))])
    for w, g in zip(want, outs):
        np.testing.assert_array_equal(w, g)
    t = fleet.last_run_telemetry
    assert t["lost_requests"] == 0
    assert len(t["decode_pool"]["kills"]) == 1
