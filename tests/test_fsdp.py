"""FullyShardedDataParallel: ZeRO-3-style param sharding on the 8-device sim.

Beyond-reference capability (SURVEY.md §2c: "FSDP / ZeRO sharding: NO —
variables mirrored, not sharded"): parameters and optimizer state shard
across the fsdp axis; training matches plain DP numerically.
"""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec

import distributed_tpu as dtpu


def _data(n=256):
    x, y = dtpu.data.synthetic_images(n, (28, 28), 10, seed=11)
    return x[..., None].astype(np.float32) / 255.0, y


def _build(strategy):
    def mk():
        m = dtpu.Model(dtpu.models.mnist_cnn())
        m.compile(optimizer=dtpu.optim.SGD(0.05, momentum=0.9),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
        return m

    if strategy is None:
        return mk()
    with strategy.scope():
        return mk()


class TestFSDP:
    def test_params_are_sharded(self, devices):
        strategy = dtpu.FullyShardedDataParallel()
        model = _build(strategy)
        model.build((28, 28, 1))
        # dense1 kernel is (5408, 64): dim 0 divisible by 8 -> sharded there.
        k = model.params["dense"]["kernel"]
        assert k.sharding.spec == PartitionSpec("fsdp", None)
        # each device holds 1/8 of the rows
        shard_shapes = {s.data.shape for s in k.addressable_shards}
        assert shard_shapes == {(k.shape[0] // 8, k.shape[1])}
        # conv kernel (3,3,1,32): only dim -1 (32) divisible by 8
        ck = model.params["conv2d"]["kernel"]
        assert ck.sharding.spec == PartitionSpec(None, None, None, "fsdp")
        # momentum shards like its param
        mom = model.opt_state.inner_state[0].trace["dense"]["kernel"]
        assert mom.sharding.spec == PartitionSpec("fsdp", None)

    def test_scalar_and_awkward_shapes_replicate(self, devices):
        strategy = dtpu.FullyShardedDataParallel()
        spec = strategy._spec_for((10,))  # 10 % 8 != 0
        assert spec == PartitionSpec()
        assert strategy._spec_for(()) == PartitionSpec()

    def test_matches_dp_numerics(self, devices):
        x, y = _data()

        def losses(strategy):
            model = _build(strategy)
            hist = model.fit(x, y, batch_size=64, epochs=2, verbose=0,
                             seed=5, shuffle=False)
            return hist.history["loss"]

        ref = losses(dtpu.DataParallel())
        fsdp = losses(dtpu.FullyShardedDataParallel())
        np.testing.assert_allclose(ref, fsdp, rtol=2e-4, atol=2e-5)

    def test_checkpoint_roundtrip_preserves_sharding(self, devices, tmp_path):
        x, y = _data(128)
        strategy = dtpu.FullyShardedDataParallel()
        model = _build(strategy)
        model.fit(x, y, batch_size=64, epochs=1, verbose=0, seed=3)
        ck = dtpu.Checkpointer(tmp_path)
        ck.save(model)

        m2 = _build(dtpu.FullyShardedDataParallel())
        ck.restore_into(m2)
        k = m2.params["dense"]["kernel"]
        # restore re-places through the strategy: still sharded, not replicated
        assert k.sharding.spec == PartitionSpec("fsdp", None)
        e1 = model.evaluate(x, y, batch_size=64, verbose=0)
        e2 = m2.evaluate(x, y, batch_size=64, verbose=0)
        assert abs(e1["loss"] - e2["loss"]) < 1e-6

    def test_transformer_under_fsdp(self, devices):
        VOCAB = 32
        rng = np.random.default_rng(1)
        starts = rng.integers(0, VOCAB, size=64)
        toks = (starts[:, None] + np.arange(17)[None]) % VOCAB
        x, y = toks[:, :-1].astype(np.int32), toks[:, 1:].astype(np.int32)
        strategy = dtpu.FullyShardedDataParallel()
        with strategy.scope():
            model = dtpu.Model(dtpu.models.transformer_lm(
                VOCAB, num_layers=1, d_model=32, num_heads=2, max_len=16))
            model.compile(optimizer=dtpu.optim.Adam(1e-2),
                          loss="sparse_categorical_crossentropy")
        hist = model.fit(x, y, batch_size=32, epochs=3, verbose=0)
        assert hist.history["loss"][-1] < hist.history["loss"][0]
