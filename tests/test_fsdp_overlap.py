"""FSDP comm/compute overlap in the scanned block stack.

The ``Strategy.overlap_spec`` x ``nn.ScannedBlocks(overlap=)`` seam: under
an FSDP-family strategy the per-layer scan prefetches layer i+1's
parameter all-gather while layer i computes (double-buffered carry; the
gather is a replicated sharding constraint, so it is layout-only and
differentiable). The contract tested here:

- numerics are IDENTICAL to the non-overlapped scan (the gather changes
  when bytes move, never what they are) at rtol 2e-5 on the loss
  trajectory;
- fit telemetry attributes the structural win: exposed-comm fraction
  1.0 (every gather serial with compute) -> 1/L (only the layer-0 warm
  gather left on the critical path);
- ``overlap='require'`` is loud under a strategy with no gather;
  ``'auto'`` silently degrades to the plain scan.

Wall-clock hiding is an accelerator claim (single-host sim shares one
execution stream) — ``bench.py overlap2`` measures and caveats it.
"""

import numpy as np
import pytest
from jax.sharding import PartitionSpec

import distributed_tpu as dtpu
from distributed_tpu.nn import scan as nn_scan


def _data(vocab=64, batch=8, seq=16, seed=0):
    rng = np.random.default_rng(seed)
    tok = rng.integers(0, vocab, (batch, seq + 1), dtype=np.int64)
    return tok[:, :-1].astype(np.int32), tok[:, 1:].astype(np.int32)


def _fit_losses(strategy, overlap, steps=3, vocab=64, seq=16):
    with strategy.scope():
        model = dtpu.Model(dtpu.models.transformer_lm(
            vocab, num_layers=2, d_model=16, num_heads=2, max_len=seq,
            scan=True, scan_overlap=overlap))
        model.compile(optimizer=dtpu.optim.Adam(1e-3),
                      loss="sparse_categorical_crossentropy")
    model.build((seq,), seed=0)
    x, y = _data(vocab=vocab, seq=seq)
    hist = model.fit(x, y, batch_size=x.shape[0], epochs=steps,
                     steps_per_epoch=1, verbose=0, seed=0)
    return [float(l) for l in hist.history["loss"]], model


def test_overlap_spec_seam(devices):
    """Base strategies opt out (None); FSDP's gather pins every ndim>=1
    leaf to the replicated layout — an explicit all-gather the scheduler
    can hoist off the critical path. The constraint only materializes
    when the gathered value is CONSUMED (GSPMD cancels an unconsumed
    gather-then-reshard), which is the scan-body situation: the gathered
    layer params feed the block's compute."""
    assert dtpu.SingleDevice().overlap_spec() is None
    assert dtpu.DataParallel().overlap_spec() is None
    fsdp = dtpu.FullyShardedDataParallel()
    gather = fsdp.overlap_spec()
    assert callable(gather)
    with fsdp.scope():
        model = dtpu.Model(dtpu.models.mnist_cnn())
        model.compile(optimizer="sgd",
                      loss="sparse_categorical_crossentropy")
        model.build((28, 28, 1))
    k = model.params["dense"]["kernel"]
    assert k.sharding.spec == PartitionSpec("fsdp", None)
    import jax

    consumed = jax.jit(lambda p: (gather(p) * 1.0).sum())
    hlo = consumed.lower(k).compile().as_text()
    assert "all-gather" in hlo
    got = float(consumed(k))
    assert got == pytest.approx(float(np.asarray(k).sum()), rel=1e-5)


def test_overlap_matches_off_numerics(devices):
    """The tentpole parity gate: gather prefetch must not change a single
    loss value beyond reordering noise."""
    ref, _ = _fit_losses(dtpu.FullyShardedDataParallel(), "off")
    got, model = _fit_losses(dtpu.FullyShardedDataParallel(), "auto")
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=0)
    telem = model.last_fit_telemetry["overlap"]
    assert telem["overlap"] is True
    assert telem["layers"] == 2
    assert telem["exposed_comm_fraction"] == pytest.approx(0.5)


def test_off_telemetry_reports_full_exposure(devices):
    _, model = _fit_losses(dtpu.FullyShardedDataParallel(), "off")
    telem = model.last_fit_telemetry["overlap"]
    assert telem["overlap"] is False
    assert telem["exposed_comm_fraction"] == 1.0


def test_auto_degrades_silently_without_gather():
    """SingleDevice has no overlap_spec: 'auto' must run the plain scan,
    report no overlap, and keep numerics."""
    losses, model = _fit_losses(dtpu.SingleDevice(), "auto", steps=2)
    ref, _ = _fit_losses(dtpu.SingleDevice(), "off", steps=2)
    np.testing.assert_allclose(losses, ref, rtol=1e-6)
    telem = model.last_fit_telemetry["overlap"]
    assert telem["overlap"] is False


def test_require_is_loud_without_gather():
    with pytest.raises(ValueError, match="overlap_spec"):
        _fit_losses(dtpu.SingleDevice(), "require", steps=1)


def test_scanned_blocks_validates_overlap_mode():
    with pytest.raises(ValueError, match="overlap"):
        dtpu.nn.ScannedBlocks(
            lambda: dtpu.nn.Dense(4), 2, overlap="sometimes")


def test_overlap_trace_records_activation(devices):
    """The threadlocal trace the fit loop reads: set by the scanned apply
    at trace time, layers + active flag."""
    _, model = _fit_losses(dtpu.FullyShardedDataParallel(), "auto", steps=1)
    rec = nn_scan.last_overlap_trace()
    assert rec == {"layers": 2, "active": True}
