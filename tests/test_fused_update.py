"""Fused Adam/AdamW Pallas kernel (ops.fused_update / optim.fused_adam).

The contract under test: the fused update is operation-for-operation the
stock optax math, so trajectories match bit-for-bit on a single device and
to float-noise (FMA regrouping inside shard_map) on a mesh — the ISSUE's
"bit-compared (or rtol <= 1e-6) against stock optax Adam over 10 steps
under SingleDevice/DP/ZeRO-1/FSDP".
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import distributed_tpu as dtpu
from distributed_tpu.ops.fused_update import FusedAdamState

STRATEGIES = {
    "single": dtpu.SingleDevice,
    "dp": dtpu.DataParallel,
    "zero1": dtpu.ZeroDataParallel,
    "fsdp": dtpu.FSDP,
}


def _tree_diff(a, b):
    return max(
        float(np.max(np.abs(
            np.asarray(x, np.float64) - np.asarray(y, np.float64))))
        if np.asarray(x).size else 0.0
        for x, y in zip(jax.tree_util.tree_leaves(jax.device_get(a)),
                        jax.tree_util.tree_leaves(jax.device_get(b)))
    )


def _assert_tree_close(a, b, rtol=1e-6, atol=1e-7):
    for x, y in zip(jax.tree_util.tree_leaves(jax.device_get(a)),
                    jax.tree_util.tree_leaves(jax.device_get(b))):
        np.testing.assert_allclose(
            np.asarray(x, np.float64), np.asarray(y, np.float64),
            rtol=rtol, atol=atol,
        )


# ------------------------------------------------------- transform level --
def _run_transform(tx, strategy, params, n_steps=10):
    opt_state = strategy.init_opt_state(tx, params)

    @jax.jit
    def one(p, s, g):
        u, s = tx.update(g, s, p)
        return optax.apply_updates(p, u), s

    key = jax.random.PRNGKey(1)
    p = params
    with strategy.scope():
        for i in range(n_steps):
            g = jax.tree_util.tree_map(
                lambda a: jax.random.normal(
                    jax.random.fold_in(key, i), a.shape, a.dtype),
                params,
            )
            p, opt_state = one(p, opt_state, g)
    return p, opt_state


@pytest.mark.parametrize("name", sorted(STRATEGIES))
def test_transform_matches_stock_adam(name):
    strategy = STRATEGIES[name]()
    with strategy.scope():
        key = jax.random.PRNGKey(0)
        params = strategy.put_params({
            "w": jax.random.normal(key, (64, 32)),
            "nest": {"k": jax.random.normal(key, (16, 8)),
                     "b": jnp.zeros((8,))},
        })
    p_stock, _ = _run_transform(dtpu.optim.Adam(1e-2), strategy, params)
    p_fused, _ = _run_transform(dtpu.optim.fused_adam(1e-2), strategy,
                                params)
    if name == "single":
        assert _tree_diff(p_stock, p_fused) == 0.0  # bit-identical
    else:
        # On a mesh the fused path runs under shard_map; XLA may contract
        # multiply-adds differently there — ulp-level, far inside the
        # acceptance rtol.
        _assert_tree_close(p_stock, p_fused)


def test_transform_matches_stock_adamw():
    strategy = dtpu.SingleDevice()
    with strategy.scope():
        params = {"w": jax.random.normal(jax.random.PRNGKey(0), (32, 16))}
    p_stock, _ = _run_transform(
        dtpu.optim.AdamW(1e-2, weight_decay=0.05), strategy, params)
    p_fused, _ = _run_transform(
        dtpu.optim.fused_adamw(1e-2, weight_decay=0.05), strategy, params)
    assert _tree_diff(p_stock, p_fused) == 0.0


def test_integer_leaves_pass_through():
    # Base factory, not the inject_hyperparams wrapper: inject (stock
    # optax behavior, fused and stock Adam alike) canonicalizes the
    # injected scalars to the first leaf's dtype, so an int-first tree is
    # its known pathology, not this kernel's.
    from distributed_tpu.ops import fused_update as fu

    tx = fu.fused_adam(1e-2)
    params = {"w": jnp.ones((8, 8)), "step_buf": jnp.arange(4)}
    state = tx.init(params)
    grads = {"w": jnp.ones((8, 8)), "step_buf": jnp.zeros(4, jnp.int32)}
    updates, _ = tx.update(grads, state, params)
    assert np.all(np.asarray(updates["step_buf"]) == 0)
    assert np.any(np.asarray(updates["w"]) != 0)


# ----------------------------------------------------------- model level --
def _fit_params(opt, strategy_cls, seed=0):
    rng = np.random.default_rng(3)
    x = rng.normal(size=(64, 16)).astype(np.float32)
    y = rng.integers(0, 8, (64,)).astype(np.int32)
    strategy = strategy_cls()
    with strategy.scope():
        m = dtpu.Model(dtpu.nn.Sequential([
            dtpu.nn.Dense(32, activation="relu"), dtpu.nn.Dense(8)
        ]))
        m.compile(optimizer=opt, loss="sparse_categorical_crossentropy")
    m.build((16,), seed=seed)
    h = m.fit(x, y, batch_size=16, epochs=1, steps_per_epoch=10, verbose=0,
              shuffle=False, prefetch=0)
    return m, h.history["loss"][-1]


@pytest.mark.slow
@pytest.mark.parametrize("name", sorted(STRATEGIES))
def test_model_10step_parity(name):
    """Full fit()-path parity sweep — @slow: the tier-1 acceptance check
    is the transform-level 10-step comparison above (all 4 strategies)
    plus the LM bit-parity below; this end-to-end sweep re-proves the
    same numbers through fit() and rides the slow lane."""
    m_stock, l_stock = _fit_params(dtpu.optim.Adam(1e-3), STRATEGIES[name])
    m_fused, l_fused = _fit_params(
        dtpu.optim.fused_adam(1e-3), STRATEGIES[name])
    assert l_fused == pytest.approx(l_stock, rel=1e-6)
    _assert_tree_close(m_stock.params, m_fused.params)


def test_lm_singledevice_bit_parity():
    """Attention LM, fused vs stock, SingleDevice: bit-identical — the
    kernel's math exactly reproduces optax's per-leaf chain."""
    rng = np.random.default_rng(0)
    tok = rng.integers(0, 64, (32, 17), dtype=np.int64)
    x, y = tok[:, :-1].astype(np.int32), tok[:, 1:].astype(np.int32)

    def run(opt):
        m = dtpu.Model(dtpu.models.transformer_lm(
            64, num_layers=1, d_model=32, num_heads=2, max_len=16))
        m.compile(optimizer=opt, loss="sparse_categorical_crossentropy")
        m.build((16,), seed=0)
        m.fit(x, y, batch_size=16, epochs=1, steps_per_epoch=4, verbose=0,
              shuffle=False, prefetch=0)
        return m.params

    assert _tree_diff(run(dtpu.optim.Adam(1e-3)),
                      run(dtpu.optim.fused_adam(1e-3))) == 0.0


# ----------------------------------------------- hyperparams + registry --
def test_learning_rate_mutation_and_registry():
    m, _ = _fit_params("fused_adam", dtpu.SingleDevice)  # registry name
    m.set_learning_rate(5e-4)
    assert m.get_learning_rate() == pytest.approx(5e-4)
    # state really is the fused kernel's (not silently stock adam)
    assert any(
        isinstance(s, FusedAdamState)
        for s in jax.tree_util.tree_leaves(
            m.opt_state, is_leaf=lambda x: isinstance(x, FusedAdamState))
    )


def test_checkpoint_roundtrip_fused_state(tmp_path):
    """Fused-Adam opt state (count + moments + injected LR) survives
    Checkpointer save/restore exactly, including a runtime-mutated LR."""
    m, _ = _fit_params(dtpu.optim.fused_adam(1e-3), dtpu.SingleDevice)
    m.set_learning_rate(2.5e-4)
    ckpt = dtpu.Checkpointer(tmp_path / "ck")
    ckpt.save(m, step=m.step)

    m2, _ = _fit_params(dtpu.optim.fused_adam(1e-3), dtpu.SingleDevice)
    ckpt.restore_into(m2)
    assert m2.get_learning_rate() == pytest.approx(2.5e-4)
    assert _tree_diff(m.opt_state, m2.opt_state) == 0.0
    assert _tree_diff(m.params, m2.params) == 0.0


def test_sharded_checkpoint_roundtrip_fused_state(tmp_path):
    """Same round-trip through ShardedCheckpointer under ZeRO-1 (the
    fused moments are data-sharded on disk and back)."""
    m, _ = _fit_params(dtpu.optim.fused_adam(1e-3), dtpu.ZeroDataParallel)
    m.set_learning_rate(1.25e-4)
    ckpt = dtpu.ShardedCheckpointer(tmp_path / "sck")
    ckpt.save(m, step=m.step)

    m2, _ = _fit_params(dtpu.optim.fused_adam(1e-3), dtpu.ZeroDataParallel)
    ckpt.restore_into(m2)
    assert m2.get_learning_rate() == pytest.approx(1.25e-4)
    assert _tree_diff(m.opt_state, m2.opt_state) == 0.0
    assert _tree_diff(m.params, m2.params) == 0.0
