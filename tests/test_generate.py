"""KV-cache generation: decode == full apply, sampling, guardrails.

The decisive test is teacher-forced consistency: stepping the cached
decode path over a sequence must reproduce the full-sequence apply()'s
logits at every position — that exercises the cache write/read, the
position masking, and the positional-embedding offset all at once.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import distributed_tpu as dtpu
from distributed_tpu import nn


def _lm(vocab=32, layers=2, d=16, heads=2, max_len=32, **kw):
    return dtpu.models.transformer_lm(
        vocab, num_layers=layers, d_model=d, num_heads=heads,
        max_len=max_len, **kw
    )


def test_decode_matches_full_apply():
    module = _lm()
    params, state, _ = module.init(jax.random.PRNGKey(0), (16,))
    x = np.random.default_rng(0).integers(0, 32, (3, 16)).astype(np.int32)

    full_logits, _ = module.apply(params, state, jnp.asarray(x))

    cache = module.init_cache(params, 3, 16, full_logits.dtype)
    got = []
    for t in range(16):
        lg, cache = module.decode(
            params, state, cache, jnp.asarray(x[:, t : t + 1]), pos=t
        )
        got.append(lg[:, 0])
    got = jnp.stack(got, axis=1)
    np.testing.assert_allclose(got, full_logits, atol=1e-4, rtol=1e-4)


# @slow (tier-1 budget, PR 16): ~10s compile; MoE decode routing parity
# stays in tier-1 layer-level (test_moe_decode_is_dropless_topk) and the
# stack-level decode-vs-apply parity is covered by the dense-LM tests.
@pytest.mark.slow
def test_decode_matches_full_apply_moe():
    """MoE FFN blocks ride the default (position-independent) decode."""
    module = _lm(moe_experts=2, moe_every=2)
    params, state, _ = module.init(jax.random.PRNGKey(1), (8,))
    x = np.random.default_rng(1).integers(0, 32, (2, 8)).astype(np.int32)
    full_logits, _ = module.apply(params, state, jnp.asarray(x))
    cache = module.init_cache(params, 2, 8, full_logits.dtype)
    got = []
    for t in range(8):
        lg, cache = module.decode(
            params, state, cache, jnp.asarray(x[:, t : t + 1]), pos=t
        )
        got.append(lg[:, 0])
    np.testing.assert_allclose(
        jnp.stack(got, axis=1), full_logits, atol=1e-4, rtol=1e-4
    )


def test_moe_decode_is_dropless_topk():
    """MoE.decode routes without capacity: under a capacity factor high
    enough that apply() drops nothing, decode must equal apply column-wise
    — even with enough experts that the low-capacity default would drop
    (the config that exposed the inherited-default-decode bug)."""
    layer = nn.MoE(4, 16, capacity_factor=16.0, group_size=8)
    params, state, _ = layer.init(jax.random.PRNGKey(0), (8, 8))
    x = jnp.asarray(
        np.random.default_rng(2).standard_normal((2, 8, 8)), jnp.float32
    )
    full, _ = layer.apply(params, state, x)
    for t in range(8):
        got, _ = layer.decode(params, state, {}, x[:, t : t + 1], pos=t)
        np.testing.assert_allclose(got[:, 0], full[:, t], atol=1e-5,
                                   rtol=1e-4)


def test_generate_shapes_and_greedy_determinism():
    model = dtpu.Model(_lm())
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    model.build((16,))
    prompt = np.array([[1, 2, 3], [4, 5, 6]], np.int32)
    out1 = model.generate(prompt, 8, temperature=0.0)
    out2 = model.generate(prompt, 8, temperature=0.0)
    assert out1.shape == (2, 11)
    np.testing.assert_array_equal(out1, out2)
    np.testing.assert_array_equal(out1[:, :3], prompt)
    assert out1.dtype == np.int32
    assert (out1 >= 0).all() and (out1 < 32).all()


def test_generate_sampling_respects_top_k_and_seed():
    model = dtpu.Model(_lm())
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    model.build((16,))
    prompt = np.array([[1, 2]], np.int32)
    a = model.generate(prompt, 6, temperature=1.0, seed=0)
    b = model.generate(prompt, 6, temperature=1.0, seed=0)
    c = model.generate(prompt, 6, temperature=1.0, seed=7)
    np.testing.assert_array_equal(a, b)  # same seed, same tokens
    assert a.shape == c.shape
    # top_k=1 must equal greedy regardless of temperature.
    g = model.generate(prompt, 6, temperature=0.0)
    k1 = model.generate(prompt, 6, temperature=1.0, top_k=1, seed=3)
    np.testing.assert_array_equal(g, k1)


def test_generate_learns_a_period_two_cycle():
    """An overfit LM must reproduce its memorized alternation greedily."""
    rng = np.random.default_rng(0)
    seq = np.tile(np.array([7, 11], np.int32), 16)[:17]  # 7,11,7,11,...
    x = np.stack([seq[:-1]] * 8)
    y = np.stack([seq[1:]] * 8)
    model = dtpu.Model(_lm(layers=1, d=32))
    model.compile(optimizer=dtpu.optim.Adam(3e-3),
                  loss="sparse_categorical_crossentropy")
    hist = model.fit(x, y, batch_size=8, epochs=60, verbose=0)
    assert hist.history["loss"][-1] < 0.2, hist.history["loss"][-5:]
    out = model.generate(np.array([[7, 11, 7]], np.int32), 6,
                         temperature=0.0)
    expect = [7, 11, 7, 11, 7, 11, 7, 11, 7]
    assert out[0].tolist() == expect, out[0].tolist()


def test_noncausal_decode_raises():
    """Bidirectional attention has no autoregressive decode; it must fail
    loudly, not silently run causal (trained-vs-decoded mismatch)."""
    mha = nn.MultiHeadAttention(2, causal=False)
    params, _, _ = mha.init(jax.random.PRNGKey(0), (4, 16))
    cache = mha.init_cache(params, 1, 4, jnp.float32)
    with pytest.raises(NotImplementedError, match="causal"):
        mha.decode(params, {}, cache, jnp.zeros((1, 1, 16)), pos=0)


def _restack_unrolled_into(pu, num_layers, container):
    """Map the unrolled LM param tree (flat residual_{2i}/residual_{2i+1})
    into a stacked container layout ({container: {"blocks": ...}})."""
    def name(i):
        return "residual" if i == 0 else f"residual_{i}"

    stacked = {}
    for slot, off in (("residual", 0), ("residual_1", 1)):
        per = [pu[name(2 * i + off)] for i in range(num_layers)]
        stacked[slot] = jax.tree_util.tree_map(
            lambda *ls: jnp.stack(ls), *per)
    ps = {k: v for k, v in pu.items() if not k.startswith("residual")}
    ps[container] = {"blocks": stacked}
    return ps


def test_generate_pipelined_matches_unrolled(devices):
    """PP-trained LMs can generate: greedy decode through the stacked stage
    caches equals the unrolled model's, both on a single device and with
    the stage stack sharded over a live 'pipe' mesh axis."""
    L = 2
    kw = dict(layers=L, d=32, heads=4, max_len=32)
    mu = dtpu.Model(_lm(vocab=64, **kw))
    mu.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    mu.build((16,), seed=7)

    prompt = np.array([[5, 9, 2, 11], [1, 1, 3, 60]], np.int32)
    want = mu.generate(prompt, 8, temperature=0.0)

    mp = dtpu.Model(_lm(vocab=64, pipeline=True, **kw))
    mp.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    mp.build((16,), seed=0)
    mp.params = _restack_unrolled_into(mu.params, L, "pipelined_blocks")
    np.testing.assert_array_equal(want, mp.generate(prompt, 8,
                                                    temperature=0.0))

    strategy = dtpu.DataPipelineParallel(devices=devices,
                                         pipeline_parallel=2)
    with strategy.scope():
        ms = dtpu.Model(_lm(vocab=64, pipeline=True, **kw))
        ms.compile(optimizer="adam",
                   loss="sparse_categorical_crossentropy")
        ms.build((16,), seed=0)
    ms.params = ms.strategy.put_params(
        _restack_unrolled_into(mu.params, L, "pipelined_blocks"),
        ms.module.sharding_hints(),
    )
    np.testing.assert_array_equal(want, ms.generate(prompt, 8,
                                                    temperature=0.0))


def test_pipelined_decode_is_memory_sharded(devices):
    """On a live pipe mesh, decode must run the ring schedule — stage
    params/caches stay resident per rank (no all-gather of the stack) and
    the activation hops via collective-permute."""
    strategy = dtpu.DataPipelineParallel(devices=devices,
                                         pipeline_parallel=2)
    with strategy.scope():
        m = dtpu.Model(_lm(vocab=64, layers=2, d=32, heads=4, max_len=32,
                           pipeline=True))
        m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
        m.build((16,), seed=0)
    module, params, state = m.module, m.params, m.state
    cache = module.init_cache(params, 1, 16, jnp.float32)

    def step(p, c, x):
        with strategy.scope():
            return module.decode(p, state, c, x, pos=3)

    hlo = (
        jax.jit(step)
        .lower(params, cache, jnp.zeros((1, 1), jnp.int32))
        .compile()
        .as_text()
    )
    assert "collective-permute" in hlo
    assert "all-gather" not in hlo


# @slow (tier-1 budget, PR 17): ~10s TP generate drive; TP numerics stay
# in-tier via TestTensorParallel::test_tp_matches_single_device
# (test_transformer.py) and greedy decode parity stays in-tier via the
# single-device generate tests + the serving decode-parity suite.
@pytest.mark.slow
def test_generate_under_tensor_parallel_matches_single_device(devices):
    """Generation must work with Megatron-sharded params and produce the
    same greedy tokens as the unsharded model."""
    x = np.random.default_rng(5).integers(0, 32, (8, 12)).astype(np.int32)
    y = np.random.default_rng(6).integers(0, 32, (8, 12)).astype(np.int32)
    prompt = np.array([[3, 1, 4]], np.int32)

    single = dtpu.Model(_lm(max_len=16))
    single.compile(optimizer=dtpu.optim.Adam(1e-3),
                   loss="sparse_categorical_crossentropy")
    single.fit(x, y, batch_size=8, epochs=2, verbose=0, seed=0)
    want = single.generate(prompt, 8, temperature=0.0)

    strategy = dtpu.DataTensorParallel(devices=devices, model_parallel=2)
    with strategy.scope():
        tp = dtpu.Model(_lm(max_len=16))
        tp.compile(optimizer=dtpu.optim.Adam(1e-3),
                   loss="sparse_categorical_crossentropy")
    tp.fit(x, y, batch_size=8, epochs=2, verbose=0, seed=0)
    got = tp.generate(prompt, 8, temperature=0.0)
    np.testing.assert_array_equal(want, got)


def test_compile_grad_clip_bounds_updates():
    """grad_clip must cap the global gradient norm actually applied."""
    import jax

    x = np.random.default_rng(0).standard_normal((16, 4)).astype(np.float32)
    y = (np.random.default_rng(1).integers(0, 2, (16,))).astype(np.int32)
    module = nn.Sequential([nn.Dense(2)])
    m = dtpu.Model(module)
    # Huge LR + tiny clip: without clipping the params would blow up.
    m.compile(optimizer=dtpu.optim.SGD(1.0), grad_clip=1e-3,
              loss="sparse_categorical_crossentropy")
    m.build((4,))
    before = jax.tree_util.tree_map(np.asarray, m.params)
    m.fit(x, y, batch_size=16, epochs=1, verbose=0)
    after = jax.tree_util.tree_map(np.asarray, m.params)
    deltas = [
        np.linalg.norm(b - a) ** 2
        for a, b in zip(
            jax.tree_util.tree_leaves(before), jax.tree_util.tree_leaves(after)
        )
    ]
    total = float(np.sqrt(sum(deltas)))
    assert total <= 1e-3 * 1.0 + 1e-6, total  # lr * clip

    with pytest.raises(ValueError, match="grad_clip"):
        dtpu.Model(nn.Sequential([nn.Dense(2)])).compile(grad_clip=-1.0)


def test_generate_beyond_positional_table_raises():
    model = dtpu.Model(_lm(max_len=8))
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    model.build((8,))
    with pytest.raises(ValueError, match="max_len"):
        model.generate(np.array([[1, 2, 3, 4]], np.int32), 16)


def test_generate_bucketing_reuses_compilation_across_prompt_lengths():
    """Varying prompt length within one 64-token bucket must not add a new
    compiled scan (prompt length is a dynamic argument; the jit cache is
    keyed on the bucketed length only) and the cache is LRU-bounded."""
    model = dtpu.Model(_lm(max_len=64))
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    model.build((16,))
    p3 = np.array([[1, 2, 3]], np.int32)
    p5 = np.array([[1, 2, 3, 4, 5]], np.int32)
    model.generate(p3, 8, temperature=0.0)
    n_compiled = len(model._generate_fns)
    out5 = model.generate(p5, 8, temperature=0.0)
    assert len(model._generate_fns) == n_compiled  # same bucket, no retrace
    assert out5.shape == (1, 13)
    np.testing.assert_array_equal(out5[:, :5], p5)
    assert len(model._generate_fns) <= dtpu.Model._GENERATE_CACHE_MAX


def test_generate_top_k_clamped_to_vocab():
    """top_k >= vocab must behave as plain sampling, not crash at trace
    time (round-2 advisor finding on the out-of-bounds sort index)."""
    model = dtpu.Model(_lm(vocab=32))
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    model.build((16,))
    prompt = np.array([[1, 2]], np.int32)
    a = model.generate(prompt, 4, temperature=1.0, top_k=32, seed=1)
    b = model.generate(prompt, 4, temperature=1.0, top_k=1000, seed=1)
    np.testing.assert_array_equal(a, b)  # both unrestricted
    with pytest.raises(ValueError, match="top_k"):
        model.generate(prompt, 4, top_k=0)


@pytest.mark.parametrize("strategy_name", ["fsdp", "sp_ring", "sp_ulysses", "ep"])
def test_generate_under_scaleout_strategies_matches_single_device(
    strategy_name, devices
):
    """VERDICT r2 weak #7: generate() was only strategy-tested under TP.
    Under FSDP/SP/EP the cached decode must produce exactly the
    single-device tokens (greedy) — or raise a named error, never silently
    diverge. Today all four work; this test pins that."""
    kw = {}
    if strategy_name == "fsdp":
        strategy = dtpu.FullyShardedDataParallel()
    elif strategy_name == "sp_ring":
        strategy = dtpu.DataSeqParallel(seq_parallel=2)
    elif strategy_name == "sp_ulysses":
        strategy = dtpu.DataSeqParallel(seq_parallel=2, attention="ulysses")
    else:
        strategy = dtpu.DataExpertParallel()
        kw = dict(moe_experts=2, moe_every=1)

    def build(strat):
        def mk():
            m = dtpu.Model(dtpu.models.transformer_lm(
                32, num_layers=1, d_model=32, num_heads=4, max_len=32, **kw))
            m.compile(optimizer="adam",
                      loss="sparse_categorical_crossentropy")
            m.build((16,))
            return m
        if strat is None:
            return mk()
        with strat.scope():
            return mk()

    prompt = np.array([[1, 2, 3], [7, 8, 9]], np.int32)
    want = build(None).generate(prompt, 6, temperature=0.0)
    got = build(strategy).generate(prompt, 6, temperature=0.0)
    np.testing.assert_array_equal(want, got)
