"""Cross-replica prefix gossip: the fleet-wide chain-hash index.

A cold replica re-earning a prefix the warm one already computed is the
gap gossip closes: replicas advertise their ``PrefixStore`` keys, the
router treats gossip-adoptable replicas as warm at placement, and the
fleet moves the blocks (``pack_prefix`` / ``adopt_prefix``) — stamped
with ``weights_version`` so stale-weights KV can NEVER travel (the
``update_weights`` flush discipline, extended fleet-wide).

Correctness bar, as everywhere in serving: whatever blocks travel, the
greedy token stream must be exactly what the gossip-off fleet computes.
The fleet tests use a TRAINED tiny model — untrained d_model=16 logits
are near-tied and their argmax flips between dispatch shapes, which
would turn placement differences into token noise.
"""

import subprocess
import sys
import textwrap

import jax
import numpy as np
import pytest

import distributed_tpu as dtpu
from distributed_tpu.fleet import ServingFleet
from distributed_tpu.fleet.gossip import PrefixGossipIndex
from distributed_tpu.fleet.handoff import (
    HandoffIncompatible, adopt_prefix, pack_prefix,
)
from distributed_tpu.serve_service import transport as tr
from distributed_tpu.serving import Engine, Request
from distributed_tpu.serving.kv_cache import _chain_hashes
from distributed_tpu.utils import event_schema as evs
from distributed_tpu.utils.events import read_events


@pytest.fixture(scope="module")
def lm():
    rng = np.random.default_rng(0)
    model = dtpu.Model(dtpu.models.transformer_lm(
        32, num_layers=2, d_model=16, num_heads=2, max_len=128))
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    model.build((16,))
    xs = rng.integers(0, 32, size=(32, 16)).astype(np.int32)
    model.fit(xs, np.roll(xs, -1, axis=1), batch_size=32, epochs=25,
              verbose=0)
    return model


def _shared_requests(rng, n=3, shared_blocks=2, block=16, new=24, seed0=0,
                     shared=None):
    """``n`` requests over one shared full-block prefix + distinct
    tails. Pass ``shared`` to reuse a prefix across calls (warm-up run
    then wave) — a fresh one is drawn otherwise."""
    if shared is None:
        shared = rng.integers(0, 32,
                              size=shared_blocks * block).astype(np.int32)
    return [
        Request(np.concatenate([
            shared, rng.integers(0, 32, size=3 + i).astype(np.int32)
        ]), new, seed=seed0 + i)
        for i in range(n)
    ]


# ------------------------------------------------------------------ index --
def test_gossip_index_protocol():
    """Advertise is REPLACE (eviction propagates), withdraw drops the
    replica, best_peer returns the longest LEADING run filtered by the
    weights-version stamp, ties break by name."""
    g = PrefixGossipIndex()
    assert g.advertise("r0", ["a", "b", "c"], weights_version=0) == 3
    assert g.advertise("r1", ["a", "b"], weights_version=0) == 2
    assert g.best_peer(["a", "b", "c", "d"], weights_version=0) == ("r0", 3)
    # leading-run semantics: a miss at key 0 means nothing is adoptable
    assert g.best_peer(["x", "a"], weights_version=0) == (None, 0)
    # tie on run length breaks by name
    assert g.best_peer(["a", "b"], weights_version=0) == ("r0", 2)
    assert g.best_peer(["a", "b"], weights_version=0,
                       exclude=("r0",)) == ("r1", 2)
    # REPLACE semantics: r0's eviction of "c" propagates on re-advertise
    assert g.advertise("r0", ["a", "b"], weights_version=0) == 0
    assert g.best_peer(["a", "b", "c"], weights_version=0)[1] == 2
    # the stamp: advertisements at the wrong version are invisible
    g.advertise("r0", ["a", "b"], weights_version=1)
    assert g.best_peer(["a", "b"], weights_version=1) == ("r0", 2)
    assert g.best_peer(["a", "b"], weights_version=2)[1] == 0
    assert g.holders("a", weights_version=1) == ["r0"]
    assert g.withdraw("r0") == 2
    assert g.telemetry()["keys_live"] == 2  # r1's advertisement remains
    assert g.telemetry()["withdrawals"] == 1


# ----------------------------------------------------------- pack / adopt --
def test_pack_adopt_roundtrip_token_exact_and_stamp(lm):
    """A warm engine's prefix blocks, adopted into a cold engine's
    store, make the cold engine admit with cached_len > 0 and decode
    exactly the same tokens; a weights-version mismatch at adoption is
    HandoffIncompatible — the satellite regression for 'flush must also
    invalidate the advertised index': even a payload packed before a
    swap dies at the stamp check."""
    rng = np.random.default_rng(1)
    reqs = _shared_requests(rng)
    prompts = [r.prompt for r in reqs]
    news = [r.max_new_tokens for r in reqs]

    warm = Engine(lm, max_slots=4, block_size=16, max_len=128,
                  prefix_cache=True)
    outs_warm = [np.asarray(o) for o in warm.run(
        [Request(p, n, seed=i) for i, (p, n) in
         enumerate(zip(prompts, news))])]
    keys = _chain_hashes(list(prompts[0][:32]), 16)
    assert len(keys) == 2 and warm.kv.prefix.peek_run(keys) != []

    payload = pack_prefix(warm.kv, keys, weights_version=0)
    assert payload is not None and payload.weights_version == 0
    assert payload.cached_len == 32

    cold = Engine(lm, max_slots=4, block_size=16, max_len=128,
                  prefix_cache=True)
    with pytest.raises(HandoffIncompatible, match="stale gossip"):
        adopt_prefix(cold.kv, payload, weights_version=1)
    assert len(cold.kv.prefix) == 0  # nothing leaked past the stamp

    assert adopt_prefix(cold.kv, payload, weights_version=0) == 2
    assert cold.kv.prefix.peek_run(keys) != []
    outs_cold = [np.asarray(o) for o in cold.run(
        [Request(p, n, seed=i) for i, (p, n) in
         enumerate(zip(prompts, news))])]
    for a, b in zip(outs_cold, outs_warm):
        assert np.array_equal(a, b)
    # the adopted blocks were USED: admissions hit the store
    assert cold.kv.prefix.hits > 0
    # adopting the same run again is a no-op (first writer wins)
    assert adopt_prefix(cold.kv, payload, weights_version=0) == 0


# -------------------------------------------------------------- transport --
def test_transport_carries_weights_version(tmp_path, lm):
    """The stamp rides both encodings (inline frame bytes and shm
    ``.npy`` dirs); manifests written before the stamp existed decode
    to None (adoption then skips the check instead of crashing)."""
    rng = np.random.default_rng(2)
    warm = Engine(lm, max_slots=2, block_size=16, max_len=128,
                  prefix_cache=True)
    reqs = _shared_requests(rng, n=2)
    warm.run(reqs)
    keys = _chain_hashes(list(reqs[0].prompt[:32]), 16)
    payload = pack_prefix(warm.kv, keys, weights_version=3)

    d = tr.handoff_to_payload(payload)
    assert d["weights_version"] == 3
    meta, blobs = tr.encode_payload(d)
    assert tr.payload_to_handoff(
        tr.decode_payload(meta, blobs)).weights_version == 3

    shm = tr.ShmTransport(tmp_path / "shm")
    ref = shm.put(d)
    got = shm.get(ref)
    assert got["weights_version"] == 3
    handoff = tr.payload_to_handoff(got)
    assert handoff.weights_version == 3
    # pre-stamp manifest: strip the field, decode must yield None
    import json
    from pathlib import Path
    mpath = Path(ref["path"]) / tr.MANIFEST
    m = json.loads(mpath.read_text())
    del m["weights_version"]
    mpath.write_text(json.dumps(m))
    assert shm.get(ref)["weights_version"] is None
    shm.close()


# ------------------------------------------------------------------ fleet --
def _warm_then_wave(lm, rng_seed, gossip, programs=None):
    """One request warms decode-0; a 3-request shared-prefix wave then
    arrives at the same instant. With gossip, the router spreads the
    wave (adoptable replicas count as warm) and the cold replica adopts
    instead of re-prefilling. Pass a shared ``programs`` when comparing
    fleets on TIME: compiled dispatches are then identical and warm, so
    TTFT differences measure scheduling, not jit tracing."""
    rng = np.random.default_rng(rng_seed)
    fl = ServingFleet(lm, decode_replicas=2, prefill_replicas=0,
                      max_slots=2, block_size=16, max_len=128,
                      prefix_cache=True, prefix_gossip=gossip,
                      programs=programs)
    shared = rng.integers(0, 32, size=32).astype(np.int32)
    warmup = _shared_requests(rng, n=1, seed0=100, shared=shared)
    wave = _shared_requests(rng, n=3, shared=shared)
    fl.run(warmup)
    out = fl.run(wave)
    return fl, out


def test_fleet_gossip_adopt_token_exact_and_ttft(lm, tmp_path,
                                                 monkeypatch):
    """The tentpole gate, in-process: the gossiping fleet adopts the
    warm replica's prefix onto the cold one (zero full re-prefills in
    the wave), finishes first tokens strictly earlier than the
    gossip-off fleet (which serializes the wave on the one warm
    replica), and the token streams are identical. Adopt/advertise
    events land in the log."""
    monkeypatch.setenv("DTPU_EVENT_LOG", str(tmp_path / "ev.jsonl"))
    # Same rng seed both runs: identical prompts, or token comparison
    # is meaningless. Shared programs: both fleets run the same warm
    # compiles, so the TTFT comparison measures scheduling.
    from distributed_tpu.fleet import EnginePrograms

    programs = EnginePrograms(lm)
    # Throwaway gossiping fleet first: the adoption path's gather/
    # scatter ops trace on their first dispatch, and that one-time wall
    # cost would be charged into the measured fleet's virtual timeline
    # (the virtual clock times REAL dispatch walls — docs/SERVING.md).
    _warm_then_wave(lm, 5, gossip=True, programs=programs)
    fl_on, out_on = _warm_then_wave(lm, 7, gossip=True,
                                    programs=programs)
    fl_off, out_off = _warm_then_wave(lm, 7, gossip=False,
                                      programs=programs)

    tel = fl_on.last_run_telemetry
    assert tel["gossip"]["adoptions"] >= 1
    assert tel["gossip"]["adopted_blocks"] >= 2
    assert tel["gossip"]["stale_rejected"] == 0
    # the wave's shared prefixes never re-prefilled from position 0:
    # the only full prefill ever was the warm-up request's first-compute
    rows = tel["decode_pool"]["replicas"]
    assert sum(r["prefills_full"] for r in rows.values()) == 1
    assert sum(r["gossip_adopts"] for r in rows.values()) >= 1
    assert sum(r["gossip_serves"] for r in rows.values()) >= 1
    # cold-replica TTFT: the gossip-off fleet pins the whole wave on
    # the warm replica (affinity), so its worst first token waits for
    # two predecessors; gossip spreads the wave and wins
    assert tel["time_to_first_token"]["max"] \
        < fl_off.last_run_telemetry["time_to_first_token"]["max"]
    for a, b in zip(out_on, out_off):
        assert np.array_equal(np.asarray(a), np.asarray(b))

    events = read_events(tmp_path / "ev.jsonl")
    adopts = [e for e in events if e["event"] == evs.PREFIX_GOSSIP_ADOPT]
    assert adopts and adopts[0]["blocks"] >= 2
    assert adopts[0]["transport"] == "inproc"
    assert any(e["event"] == evs.PREFIX_GOSSIP_ADVERTISE for e in events)


def test_fleet_update_weights_invalidates_gossip(lm):
    """The satellite fix, fleet-wide: a weight swap flushes every
    replica's prefix store AND withdraws every advertisement, and bumps
    the version — so post-swap traffic re-earns its prefixes instead of
    adopting one-update-old KV."""
    fl, _ = _warm_then_wave(lm, 9, gossip=True)
    assert fl.gossip.telemetry()["keys_live"] > 0
    same = jax.tree_util.tree_map(lambda x: x, lm.params)
    assert fl.update_weights(same) == 1
    assert fl.weights_version == 1
    assert fl.gossip.telemetry()["keys_live"] == 0
    for rep in fl.decode_pool.values():
        assert len(rep.kv.prefix) == 0
    # a shape-mismatched tree fails loud, version unmoved
    bad = jax.tree_util.tree_map(
        lambda x: np.zeros((2, 2), np.float32), lm.params
    )
    with pytest.raises(ValueError):
        fl.update_weights(bad)
    assert fl.weights_version == 1
    # post-swap traffic runs clean at the new version: full re-prefill
    # once, then advertisements resume at version 1
    rng = np.random.default_rng(10)
    fl.run(_shared_requests(rng, n=2, seed0=50))
    tel = fl.last_run_telemetry
    assert tel["gossip"]["weights_version"] == 1
    assert tel["gossip"]["stale_rejected"] == 0
    assert fl.gossip.telemetry()["keys_live"] > 0


# ------------------------------------------------------- real process @slow --
@pytest.mark.slow
def test_shm_payload_crosses_a_real_process(tmp_path, lm):
    """The same-host deployment shape: the warm side commits the payload
    to tmpfs (atomic rename), a SEPARATE process (jax-free, like the
    router) opens it and validates manifest + blocks, and the local
    adopter installs from the committed dir token-exactly."""
    rng = np.random.default_rng(3)
    warm = Engine(lm, max_slots=4, block_size=16, max_len=128,
                  prefix_cache=True)
    reqs = _shared_requests(rng)
    outs_warm = [np.asarray(o) for o in warm.run(reqs)]
    keys = _chain_hashes(list(reqs[0].prompt[:32]), 16)
    payload = pack_prefix(warm.kv, keys, weights_version=5)
    shm = tr.ShmTransport(tmp_path / "shm")
    ref = shm.put(tr.handoff_to_payload(payload))

    # The child loads transport.py by FILE PATH: the module itself is
    # jax-free (the dtpu-lint rule), and a router-style process that
    # avoids the package __init__ chain never pays the jax import.
    tpath = tr.__file__

    child = textwrap.dedent(f"""
        import importlib.util, sys
        spec = importlib.util.spec_from_file_location("t", {tpath!r})
        tr = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(tr)
        assert "jax" not in sys.modules  # the router process stays jax-free
        p = tr.ShmTransport({str(tmp_path / "shm")!r}, owner=False).get(
            {ref!r})
        assert p["weights_version"] == 5
        assert p["cached_len"] == 32 and p["block_size"] == 16
        assert len(p["blocks"]) > 0
        for a in p["blocks"].values():
            assert a.size > 0
        assert "jax" not in sys.modules
        print("CHILD_OK")
    """)
    proc = subprocess.run([sys.executable, "-c", child],
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "CHILD_OK" in proc.stdout

    cold = Engine(lm, max_slots=4, block_size=16, max_len=128,
                  prefix_cache=True)
    got = tr.payload_to_handoff(shm.get(ref))
    assert adopt_prefix(cold.kv, got, weights_version=5) == 2
    outs_cold = [np.asarray(o) for o in cold.run(
        [Request(r.prompt, r.max_new_tokens, seed=r.seed) for r in reqs])]
    for a, b in zip(outs_cold, outs_warm):
        assert np.array_equal(a, b)
    shm.close()
