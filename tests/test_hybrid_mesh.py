"""Multi-slice (DCN) hybrid mesh construction (VERDICT r2 item 10):
make_mesh(..., dcn_axis='data') lays the data axis across slices so only
the gradient all-reduce crosses DCN while model/fsdp axes stay on a
slice's ICI. The 8-device sim mocks a 2-slice system via slice_ids."""

import numpy as np
import pytest

import distributed_tpu as dtpu
from distributed_tpu.parallel.mesh import _hybrid_device_array, make_mesh


def _mock_slices(devices, per_slice):
    return [i // per_slice for i in range(len(devices))]


def test_data_axis_lays_across_slices(devices):
    ids = _mock_slices(devices, 4)  # two "slices" of 4 devices
    mesh = make_mesh({"data": 4, "model": 2}, devices=devices,
                     dcn_axis="data", slice_ids=ids)
    assert mesh.axis_names == ("data", "model")
    slice_of = {d.id: s for d, s in zip(devices, ids)}
    # Along 'data': first half slice 0, second half slice 1.
    arr = mesh.devices
    for di in range(4):
        expect = 0 if di < 2 else 1
        for mi in range(2):
            assert slice_of[arr[di, mi].id] == expect, (di, mi)
    # Along 'model' (the ICI axis): never crosses a slice boundary.
    for di in range(4):
        assert len({slice_of[arr[di, mi].id] for mi in range(2)}) == 1


def test_fsdp_within_slice_data_across(devices):
    ids = _mock_slices(devices, 4)
    mesh = make_mesh({"data": 2, "fsdp": 4}, devices=devices,
                     dcn_axis="data", slice_ids=ids)
    slice_of = {d.id: s for d, s in zip(devices, ids)}
    arr = mesh.devices
    for di in range(2):
        spans = {slice_of[arr[di, fi].id] for fi in range(4)}
        assert spans == {di}, spans  # whole fsdp line inside one slice


def test_single_slice_ignores_dcn_axis(devices):
    mesh = make_mesh({"data": 8}, devices=devices, dcn_axis="data")
    assert mesh.shape["data"] == 8  # plain path, no error


def test_errors(devices):
    ids = _mock_slices(devices, 4)
    with pytest.raises(ValueError, match="not among"):
        make_mesh({"data": 8}, devices=devices, dcn_axis="model",
                  slice_ids=ids)
    with pytest.raises(ValueError, match="not divisible"):
        make_mesh({"data": 1, "model": 8}, devices=devices,
                  dcn_axis="data", slice_ids=ids)
    with pytest.raises(ValueError, match="slice_ids"):
        make_mesh({"data": 8}, devices=devices, dcn_axis="data",
                  slice_ids=[0, 1])
    # Unbalanced slices are rejected, not silently misarranged.
    bad = [0] * 3 + [1] * 5
    with pytest.raises(ValueError, match="devices"):
        make_mesh({"data": 2, "model": 4}, devices=devices,
                  dcn_axis="data", slice_ids=bad)


def test_strategy_over_hybrid_mesh_trains(devices):
    """A DataTensorParallel strategy on the hybrid mesh runs a real train
    step (the v4-64-shaped config: data across slices, model within)."""
    ids = _mock_slices(devices, 4)
    mesh = make_mesh({"data": 4, "model": 2}, devices=devices,
                     dcn_axis="data", slice_ids=ids)
    strategy = dtpu.DataTensorParallel(mesh=mesh)
    with strategy.scope():
        m = dtpu.Model(dtpu.models.transformer_lm(
            32, num_layers=1, d_model=32, num_heads=4, max_len=16))
        m.compile(optimizer=dtpu.optim.Adam(1e-2),
                  loss="sparse_categorical_crossentropy")
    rng = np.random.default_rng(0)
    tok = rng.integers(0, 32, (8, 17)).astype(np.int32)
    hist = m.fit(tok[:, :-1], tok[:, 1:], batch_size=8, epochs=2, verbose=0)
    assert hist.history["loss"][-1] < hist.history["loss"][0]
