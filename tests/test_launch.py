"""Launcher tests: gang spawn, config injection, result/error gather.

These reproduce the reference's launcher semantics without Spark
(SURVEY.md §7 hard parts): barrier-style gang scheduling
(/root/reference/README.md:179), rank + peer-list injection
(README.md:180-183), and tryCatch-style error-as-result rows
(README.md:176, 221).
"""

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from distributed_tpu.launch import LocalLauncher

REPO = str(Path(__file__).resolve().parent.parent)


def write_worker(tmp_path, body):
    script = tmp_path / "worker.py"
    script.write_text(
        textwrap.dedent(
            f"""
            import os, sys, json
            sys.path.insert(0, {REPO!r})
            """
        )
        + textwrap.dedent(body)
    )
    return str(script)


@pytest.mark.smoke
def test_config_injection_and_results(tmp_path):
    script = write_worker(
        tmp_path,
        """
        from distributed_tpu.cluster import from_env
        from distributed_tpu.launch import report_result
        spec = from_env()
        report_result({"rank": spec.index, "n": spec.num_processes,
                       "peers": spec.workers})
        """,
    )
    results = LocalLauncher().run([sys.executable, script], 3, timeout=60)
    assert len(results) == 3
    assert all(r.ok for r in results)
    ranks = sorted(r.value["rank"] for r in results)
    assert ranks == [0, 1, 2]
    assert all(r.value["n"] == 3 for r in results)
    # Every worker sees the same rank-ordered peer list (README.md:84-114).
    peers = {tuple(r.value["peers"]) for r in results}
    assert len(peers) == 1


def test_error_capture_as_result_row(tmp_path):
    script = write_worker(
        tmp_path,
        """
        from distributed_tpu.cluster import from_env
        spec = from_env()
        if spec.index == 1:
            raise RuntimeError("boom on worker 1")
        from distributed_tpu.launch import report_result
        report_result("fine")
        """,
    )
    results = LocalLauncher().run([sys.executable, script], 2, timeout=60, grace=5)
    by_rank = {r.index: r for r in results}
    assert by_rank[0].ok and by_rank[0].value == "fine"
    assert not by_rank[1].ok
    assert "boom on worker 1" in by_rank[1].log_tail


def test_cli_end_to_end(tmp_path):
    script = write_worker(
        tmp_path,
        """
        from distributed_tpu.cluster import from_env
        from distributed_tpu.launch import report_result
        report_result(from_env().index * 10)
        """,
    )
    out = tmp_path / "results.json"
    proc = subprocess.run(
        [sys.executable, "-m", "distributed_tpu.launch",
         "--num-workers", "2", "--results-json", str(out), script],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rows = json.loads(out.read_text())
    assert sorted(r["value"] for r in rows) == [0, 10]


@pytest.mark.slow
def test_distributed_training_via_launcher(tmp_path):
    """Full stack: gang launch -> jax.distributed over CPU processes -> DP
    train -> identical metrics on every worker (the reference's invariant,
    README.md:226-232)."""
    script = write_worker(
        tmp_path,
        """
        import os
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        import jax
        jax.config.update("jax_platforms", "cpu")
        import numpy as np
        import distributed_tpu as dtpu
        from distributed_tpu.launch import report_result

        spec = dtpu.cluster.initialize()
        x, y = dtpu.data.synthetic_images(256, (28, 28), 10, 0)
        x = x[..., None].astype(np.float32) / 255.0

        strategy = dtpu.DataParallel()
        with strategy.scope():
            m = dtpu.Model(dtpu.models.mnist_cnn())
            m.compile(optimizer=dtpu.optim.SGD(0.05), metrics=["accuracy"])
        hist = m.fit(x, y.astype(np.int32), batch_size=64, epochs=2,
                     steps_per_epoch=3, verbose=0, seed=0)
        report_result({"rank": spec.index,
                       "acc": hist.metrics["accuracy"][-1],
                       "loss": hist.metrics["loss"][-1]})
        """,
    )
    results = LocalLauncher().run([sys.executable, script], 2, timeout=300)
    assert all(r.ok for r in results), [(r.index, r.error, r.log_tail[-500:]) for r in results]
    accs = {r.value["acc"] for r in results}
    losses = {r.value["loss"] for r in results}
    assert len(accs) == 1 and len(losses) == 1  # replicas in lockstep


# @slow (tier-1 budget, PR 17): ~7s hung-worker wait; config
# injection, error-capture, and CLI end-to-end stay in-tier, and the
# restart-after-hang path is already @slow alongside this.
@pytest.mark.slow
def test_liveness_timeout_kills_hung_worker(tmp_path):
    """A worker that goes silent (SIGSTOP — alive but not beating) is
    killed with a 'liveness timeout' row within liveness_timeout, and its
    peers are gang-killed within grace — instead of everyone burning the
    full run timeout (VERDICT r4 missing #3)."""
    import time as _time

    script = write_worker(
        tmp_path,
        """
        import signal, time
        from distributed_tpu.cluster.config import from_env
        from distributed_tpu.launch import heartbeat, report_result

        spec = from_env()
        for i in range(400):
            heartbeat(min_interval=0.0)
            time.sleep(0.05)
            if spec.index == 1 and i == 8:
                signal.raise_signal(signal.SIGSTOP)
        report_result({"rank": spec.index})
        """,
    )
    t0 = _time.time()
    results = LocalLauncher().run(
        [sys.executable, script], 2,
        timeout=300, grace=2.0, liveness_timeout=2.0,
    )
    elapsed = _time.time() - t0
    by_rank = {r.index: r for r in results}
    assert not by_rank[1].ok
    assert "liveness timeout" in by_rank[1].error, by_rank[1].error
    assert not by_rank[0].ok  # gang semantics took the survivor too
    assert "peer failure" in by_rank[0].error, by_rank[0].error
    # The whole point: detection happened in ~liveness_timeout+grace,
    # not the 300s run timeout (generous bound for slow CI).
    assert elapsed < 60, elapsed


@pytest.mark.slow
def test_hung_worker_triggers_restart_and_resume(tmp_path):
    """End-to-end elastic recovery from a HANG (not a crash): worker 1
    SIGSTOPs itself mid-epoch on the first attempt; the liveness probe
    treats the stalled heartbeat as a failure, run_with_restart relaunches
    the gang, and ModelCheckpoint(restore=True) finishes the run with
    weights bit-identical to an uninterrupted one."""
    import time as _time

    marker = tmp_path / "hung_once"
    body = f"""
        import os, signal
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        import jax
        jax.config.update("jax_platforms", "cpu")
        import numpy as np
        import distributed_tpu as dtpu
        from distributed_tpu.launch import report_result
        from distributed_tpu.training.callbacks import Callback, ModelCheckpoint

        spec = dtpu.cluster.initialize()
        x, y = dtpu.data.synthetic_images(512, (28, 28), 10, 0)
        x = x[..., None].astype(np.float32) / 255.0

        CKPT = os.environ["TEST_CKPT_DIR"]
        MARKER = {str(marker)!r}

        class HangOnce(Callback):
            # Worker 1 goes silent mid-epoch-2 on the first attempt only:
            # SIGSTOP freezes the process without killing it — exactly the
            # failure mode exit-code monitoring cannot see.
            def on_batch_end(self, model, step, logs):
                if (spec.index == 1 and step == 5
                        and not os.path.exists(MARKER)):
                    open(MARKER, "w").close()
                    signal.raise_signal(signal.SIGSTOP)

        strategy = dtpu.DataParallel()
        with strategy.scope():
            m = dtpu.Model(dtpu.models.mnist_cnn())
            m.compile(optimizer=dtpu.optim.SGD(0.05), metrics=["accuracy"])
        cbs = [ModelCheckpoint(CKPT, save_freq=3, restore=True), HangOnce()]
        hist = m.fit(x, y.astype(np.int32), batch_size=64, epochs=3,
                     steps_per_epoch=4, verbose=0, seed=0, callbacks=cbs)
        leaf = np.asarray(
            jax.tree_util.tree_leaves(m.params)[0]).ravel()[:4]
        report_result({{"rank": spec.index,
                       "loss": hist.metrics["loss"][-1],
                       "acc": hist.metrics["accuracy"][-1],
                       "leaf": [float(v) for v in leaf],
                       "epochs": hist.epoch}})
        """
    script = write_worker(tmp_path, body)

    from distributed_tpu.launch import run_with_restart

    env = {"TEST_CKPT_DIR": str(tmp_path / "ckpt")}
    t0 = _time.time()
    results = run_with_restart(
        LocalLauncher(env_extra=env), [sys.executable, script], 2,
        max_restarts=2, restart_backoff=0.1, timeout=600, grace=5,
        liveness_timeout=5.0,
    )
    elapsed = _time.time() - t0
    assert all(r.ok for r in results), [
        (r.index, r.error, r.log_tail[-600:]) for r in results
    ]
    assert marker.exists()  # the hang actually happened
    # Liveness (not the 600s timeout) must have driven the recovery.
    assert elapsed < 300, elapsed

    # Uninterrupted reference run: fresh checkpoint dir, no hang.
    marker.touch()  # HangOnce disarmed
    env2 = {"TEST_CKPT_DIR": str(tmp_path / "ckpt_ref")}
    ref = LocalLauncher(env_extra=env2).run(
        [sys.executable, script], 2, timeout=600
    )
    assert all(r.ok for r in ref), [
        (r.index, r.error, r.log_tail[-600:]) for r in ref
    ]
    got = {r.index: r.value for r in results}
    want = {r.index: r.value for r in ref}
    for rank in (0, 1):
        assert got[rank]["loss"] == want[rank]["loss"]
        assert got[rank]["acc"] == want[rank]["acc"]
        assert got[rank]["leaf"] == want[rank]["leaf"]


@pytest.mark.slow
def test_auto_restart_resumes_from_checkpoint(tmp_path):
    """Elastic recovery (the reference's self-documented gap, README.md:400):
    worker 1 dies mid-train on the first attempt; run_with_restart relaunches
    the gang, ModelCheckpoint(restore=True) resumes from the last complete
    checkpoint, and the finished run's weights + metrics are bit-identical
    to an uninterrupted run (the (seed, pass)-keyed resume math)."""
    marker = tmp_path / "died_once"
    body = f"""
        import os
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        import jax
        jax.config.update("jax_platforms", "cpu")
        import numpy as np
        import distributed_tpu as dtpu
        from distributed_tpu.launch import report_result
        from distributed_tpu.training.callbacks import Callback, ModelCheckpoint

        spec = dtpu.cluster.initialize()
        x, y = dtpu.data.synthetic_images(512, (28, 28), 10, 0)
        x = x[..., None].astype(np.float32) / 255.0

        CKPT = os.environ["TEST_CKPT_DIR"]
        MARKER = {str(marker)!r}

        class DieOnce(Callback):
            # Worker 1 hard-exits mid-epoch-2 on the first attempt only.
            def on_batch_end(self, model, step, logs):
                if (spec.index == 1 and step == 5
                        and not os.path.exists(MARKER)):
                    open(MARKER, "w").close()
                    os._exit(17)

        strategy = dtpu.DataParallel()
        with strategy.scope():
            m = dtpu.Model(dtpu.models.mnist_cnn())
            m.compile(optimizer=dtpu.optim.SGD(0.05), metrics=["accuracy"])
        cbs = [ModelCheckpoint(CKPT, save_freq=3, restore=True), DieOnce()]
        hist = m.fit(x, y.astype(np.int32), batch_size=64, epochs=3,
                     steps_per_epoch=4, verbose=0, seed=0, callbacks=cbs)
        leaf = np.asarray(
            jax.tree_util.tree_leaves(m.params)[0]).ravel()[:4]
        report_result({{"rank": spec.index,
                       "loss": hist.metrics["loss"][-1],
                       "acc": hist.metrics["accuracy"][-1],
                       "leaf": [float(v) for v in leaf],
                       "epochs": hist.epoch}})
        """
    script = write_worker(tmp_path, body)

    from distributed_tpu.launch import run_with_restart

    env = {"TEST_CKPT_DIR": str(tmp_path / "ckpt")}
    results = run_with_restart(
        LocalLauncher(env_extra=env), [sys.executable, script], 2,
        max_restarts=2, restart_backoff=0.1, timeout=300, grace=5,
    )
    assert all(r.ok for r in results), [
        (r.index, r.error, r.log_tail[-600:]) for r in results
    ]
    assert marker.exists()  # the failure actually happened

    # Uninterrupted reference run: fresh checkpoint dir, no killing.
    marker.touch()  # DieOnce disarmed
    env2 = {"TEST_CKPT_DIR": str(tmp_path / "ckpt_ref")}
    ref = LocalLauncher(env_extra=env2).run(
        [sys.executable, script], 2, timeout=300
    )
    assert all(r.ok for r in ref), [
        (r.index, r.error, r.log_tail[-600:]) for r in ref
    ]
    got = {r.index: r.value for r in results}
    want = {r.index: r.value for r in ref}
    for rank in (0, 1):
        assert got[rank]["loss"] == want[rank]["loss"]
        assert got[rank]["acc"] == want[rank]["acc"]
        assert got[rank]["leaf"] == want[rank]["leaf"]


@pytest.mark.slow
def test_explicit_coordinator_gathers_real_worker_list(tmp_path):
    """initialize(coordinator=...) must return a REAL rank-ordered worker
    list on every process (gathered collectively), not placeholders."""
    script = write_worker(
        tmp_path,
        """
        import os
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        import jax
        jax.config.update("jax_platforms", "cpu")
        import distributed_tpu as dtpu
        from distributed_tpu.cluster import from_env
        from distributed_tpu.launch import report_result

        env_spec = from_env()
        spec = dtpu.cluster.initialize(
            coordinator=env_spec.coordinator,
            num_processes=env_spec.num_processes,
            process_id=env_spec.index,
        )
        report_result({"rank": spec.index, "workers": spec.workers})
        """,
    )
    results = LocalLauncher().run([sys.executable, script], 2, timeout=120)
    assert all(r.ok for r in results), [
        (r.index, r.error, r.log_tail[-500:]) for r in results
    ]
    for r in results:
        workers = r.value["workers"]
        assert len(workers) == 2
        assert not any(w.startswith("?") for w in workers)
        host0 = workers[0].rsplit(":", 1)[0]
        assert host0 not in ("", "?")
    # identical list on both ranks (collective gather)
    assert results[0].value["workers"] == results[1].value["workers"]


@pytest.mark.slow
def test_spark_barrier_flow_end_to_end(tmp_path):
    """The reference's full Spark-barrier workflow without Spark
    (/root/reference/README.md:170-247): gang-scheduled workers receive a
    barrier-style peer list + own rank, build their cluster spec with
    from_barrier (strip the scheduler's ports, re-port 8000+seq,
    README.md:180-183), train data-parallel, and return max accuracy AS A
    STRING per worker (README.md:220) — except rank 0, which returns the
    base64-encoded HDF5 model (README.md:236-247). The driver collects one
    row per worker, checks the replica-identical-accuracy invariant
    (README.md:226-232), and decodes rank 0's row into a model file."""
    script = write_worker(
        tmp_path,
        """
        import os
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        import jax
        jax.config.update("jax_platforms", "cpu")
        import numpy as np
        import distributed_tpu as dtpu
        from distributed_tpu.cluster import from_barrier, from_env
        from distributed_tpu.launch import report_result

        # The gang launcher plays Spark's barrier: its injected spec is the
        # stand-in for barrier$address / barrier$partition. Re-derive a
        # Spark-shaped peer list (scheduler-owned ports) and rebuild the
        # spec the way the reference's closure does.
        injected = from_env()
        barrier_addresses = [
            f"{w.rsplit(':', 1)[0]}:{7077 + i}"
            for i, w in enumerate(injected.workers)
        ]
        spec = from_barrier(barrier_addresses, injected.index,
                            base_port=23840)
        os.environ["DTPU_CONFIG"] = spec.to_json()
        spec = dtpu.cluster.initialize()

        x, y = dtpu.data.synthetic_images(256, (28, 28), 10, 0)
        x = x[..., None].astype(np.float32) / 255.0
        strategy = dtpu.DataParallel()
        with strategy.scope():
            m = dtpu.Model(dtpu.models.mnist_cnn())
            m.compile(optimizer=dtpu.optim.SGD(0.05), metrics=["accuracy"])
        hist = m.fit(x, y.astype(np.int32), batch_size=64, epochs=2,
                     steps_per_epoch=3, verbose=0, seed=0)
        acc = str(max(hist.metrics["accuracy"]))
        if spec.index == 0:
            import tempfile
            path = os.path.join(tempfile.mkdtemp(), "trained-0.hdf5")
            dtpu.checkpoint.export_hdf5(path, m.params)
            report_result({"row": dtpu.checkpoint.artifact_encode(path),
                           "acc": acc})
        else:
            report_result({"row": acc, "acc": acc})
        """,
    )
    results = LocalLauncher().run([sys.executable, script], 2, timeout=300)
    assert all(r.ok for r in results), [
        (r.index, r.error, r.log_tail[-500:]) for r in results
    ]
    by_rank = {r.index: r for r in results}
    assert len(by_rank) == 2  # one row per worker, like collect()
    # Replica-identity invariant: identical accuracy strings on all workers.
    accs = {r.value["acc"] for r in results}
    assert len(accs) == 1, accs
    # Rank 0's row is the artifact; decode it like the reference's driver.
    from distributed_tpu.checkpoint import artifact_decode, import_hdf5

    out = tmp_path / "model.hdf5"
    artifact_decode(by_rank[0].value["row"], str(out))
    params, _ = import_hdf5(str(out))
    assert "conv2d" in params and "dense" in params
    # Rank 1's row is a parseable accuracy in [0, 1] (README.md:226-232).
    assert 0.0 <= float(by_rank[1].value["row"]) <= 1.0
