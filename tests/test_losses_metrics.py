import pytest
import jax
import jax.numpy as jnp
import numpy as np

from distributed_tpu.ops import losses, metrics


@pytest.mark.smoke
def test_sparse_cce_matches_manual():
    logits = jnp.array([[2.0, 1.0, 0.0], [0.0, 0.0, 5.0]])
    labels = jnp.array([0, 2])
    got = losses.sparse_categorical_crossentropy(logits, labels)
    logp = jax.nn.log_softmax(logits)
    want = -(logp[0, 0] + logp[1, 2]) / 2
    assert jnp.allclose(got, want)


def test_loss_class_form():
    fn = losses.SparseCategoricalCrossentropy(from_logits=True)
    logits = jnp.array([[10.0, 0.0]])
    assert float(fn(logits, jnp.array([0]))) < 1e-3


def test_per_example_consistent_with_mean():
    logits = jax.random.normal(jax.random.PRNGKey(0), (32, 10))
    labels = jax.random.randint(jax.random.PRNGKey(1), (32,), 0, 10)
    mean = losses.sparse_categorical_crossentropy(logits, labels)
    per = losses.get_per_example(losses.sparse_categorical_crossentropy)(logits, labels)
    assert per.shape == (32,)
    assert jnp.allclose(jnp.mean(per), mean, rtol=1e-5)


def test_accuracy_sum_count():
    logits = jnp.array([[1.0, 0.0], [0.0, 1.0], [1.0, 0.0]])
    labels = jnp.array([0, 1, 1])
    s, c = metrics.accuracy(logits, labels)
    assert (float(s), float(c)) == (2.0, 3.0)


def test_top_k():
    m = metrics.get("top_5_accuracy")
    logits = jnp.tile(jnp.arange(10.0), (4, 1))
    labels = jnp.array([9, 5, 4, 0])
    s, c = m(logits, labels)
    assert float(s) == 2.0  # classes 9 and 5 are in top-5


def test_cross_entropy_with_ignore():
    logits = jax.random.normal(jax.random.PRNGKey(0), (2, 5, 10))
    labels = jnp.full((2, 5), -100)
    labels = labels.at[0, 0].set(3)
    loss = losses.cross_entropy_with_ignore(logits, labels)
    want = losses.sparse_categorical_crossentropy(logits[0:1, 0], jnp.array([3]))
    assert jnp.allclose(loss, want, rtol=1e-5)


def test_optimizer_registry_zoo():
    """Every registered optimizer trains a step; schedules are callables."""
    import pytest
    import distributed_tpu as dtpu
    from distributed_tpu import optim

    x = np.random.default_rng(0).standard_normal((8, 4)).astype(np.float32)
    y = (np.arange(8) % 2).astype(np.int32)
    for name in ("sgd", "adam", "adamw", "rmsprop", "adagrad", "lamb"):
        m = dtpu.Model(dtpu.nn.Sequential([dtpu.nn.Dense(2)]))
        m.compile(optimizer=name, loss="sparse_categorical_crossentropy")
        h = m.fit(x, y, batch_size=8, epochs=1, steps_per_epoch=1, verbose=0)
        assert np.isfinite(h.history["loss"][0]), name
    with pytest.raises(ValueError):
        optim.get("nope")
    sched = optim.cosine_schedule(0.1, steps=100, warmup=10)
    assert callable(sched) and float(sched(0)) <= 0.1
    exp = optim.exponential_schedule(0.1, 0.9, 10, warmup=5)
    assert callable(exp)
    m = dtpu.Model(dtpu.nn.Sequential([dtpu.nn.Dense(2)]))
    m.compile(optimizer=optim.SGD(optim.cosine_schedule(0.1, 100)),
              loss="sparse_categorical_crossentropy")
    h = m.fit(x, y, batch_size=8, epochs=1, steps_per_epoch=1, verbose=0)
    assert np.isfinite(h.history["loss"][0])
