"""MoE layer + expert parallelism (DataExpertParallel).

Beyond-reference capability (SURVEY.md §2c "Expert parallelism: NO"):
routing correctness, capacity enforcement, aux-loss gradient flow, and
expert-sharded training on the 8-device sim.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec

import distributed_tpu as dtpu
from distributed_tpu import nn


def _moe(e=4, h=16, **kw):
    return nn.MoE(e, h, **kw)


class TestMoELayer:
    def test_output_shape_2d_and_3d(self):
        layer = _moe()
        params, state, out = layer.init(jax.random.PRNGKey(0), (8,))
        assert out == (8,)
        y, st = layer.apply(params, state, jnp.ones((4, 8)))
        assert y.shape == (4, 8)
        assert "aux_loss" in st
        y3, _ = layer.apply(params, state, jnp.ones((2, 6, 8)))
        assert y3.shape == (2, 6, 8)

    def test_top1_routes_to_argmax_expert(self):
        # With capacity >= all tokens and top_k=1, each token's output must
        # equal its argmax expert's MLP applied to it.
        layer = _moe(e=3, h=8, top_k=1, capacity_factor=10.0)
        params, state, _ = layer.init(jax.random.PRNGKey(1), (5,))
        x = jax.random.normal(jax.random.PRNGKey(2), (6, 5))
        y, _ = layer.apply(params, state, x)
        logits = x @ params["router"]
        chosen = jnp.argmax(logits, axis=-1)
        for i in range(6):
            e = int(chosen[i])
            hid = jax.nn.gelu(x[i] @ params["w_in"][e] + params["b_in"][e])
            ref = hid @ params["w_out"][e] + params["b_out"][e]
            np.testing.assert_allclose(y[i], ref, rtol=1e-4, atol=1e-5)

    def test_group_routing_is_exact(self):
        # Routing in small groups must not change per-token outputs when
        # capacity is generous (group structure only bounds buffer sizes).
        layer = _moe(e=3, h=8, top_k=1, capacity_factor=10.0, group_size=4)
        params, state, _ = layer.init(jax.random.PRNGKey(8), (5,))
        x = jax.random.normal(jax.random.PRNGKey(9), (12, 5))
        y, _ = layer.apply(params, state, x)
        chosen = jnp.argmax(x @ params["router"], axis=-1)
        for i in range(12):
            e = int(chosen[i])
            hid = jax.nn.gelu(x[i] @ params["w_in"][e] + params["b_in"][e])
            ref = hid @ params["w_out"][e] + params["b_out"][e]
            np.testing.assert_allclose(y[i], ref, rtol=1e-4, atol=1e-5)

    def test_prime_token_count_pads_not_degenerates(self):
        # Round-1 weakness: group size used to shrink to the largest divisor
        # of n_tokens — 1 for primes — collapsing capacity. Now tokens pad
        # up to a group boundary instead: for prime n=13 with group_size=8,
        # groups stay width 8 and routing stays exact.
        layer = _moe(e=3, h=8, top_k=1, capacity_factor=10.0, group_size=8)
        assert layer._group_size(13) == 8  # not 1
        params, state, _ = layer.init(jax.random.PRNGKey(10), (5,))
        x = jax.random.normal(jax.random.PRNGKey(11), (13, 5))
        y, st = layer.apply(params, state, x)
        chosen = jnp.argmax(x @ params["router"], axis=-1)
        for i in range(13):
            e = int(chosen[i])
            hid = jax.nn.gelu(x[i] @ params["w_in"][e] + params["b_in"][e])
            ref = hid @ params["w_out"][e] + params["b_out"][e]
            np.testing.assert_allclose(y[i], ref, rtol=1e-4, atol=1e-5)
        # aux loss is averaged over valid tokens only: a uniform router
        # should give ~weight*1 regardless of padding.
        uniform = dict(params, router=jnp.zeros_like(params["router"]))
        _, st_u = layer.apply(uniform, state, x)
        assert float(st_u["aux_loss"]) == pytest.approx(
            layer.aux_loss_weight, rel=1e-5)

    def test_capacity_drops_overflow(self):
        # capacity_factor tiny -> cap = 1 slot/expert; most tokens dropped
        # (output 0 = pass-through in a residual block).
        layer = _moe(e=2, h=4, top_k=1, capacity_factor=1e-9)
        params, state, _ = layer.init(jax.random.PRNGKey(3), (4,))
        x = jax.random.normal(jax.random.PRNGKey(4), (8, 4))
        y, _ = layer.apply(params, state, x)
        # at most 2 tokens (1 per expert) produce nonzero output
        nonzero = np.sum(np.any(np.abs(np.asarray(y)) > 1e-7, axis=-1))
        assert nonzero <= 2

    def test_aux_loss_flows_gradients_to_router(self):
        layer = _moe(e=4, h=8)
        params, state, _ = layer.init(jax.random.PRNGKey(5), (8,))
        x = jax.random.normal(jax.random.PRNGKey(6), (16, 8))

        def loss(p):
            _, st = layer.apply(p, state, x)
            return st["aux_loss"]

        g = jax.grad(loss)(params)
        assert float(jnp.max(jnp.abs(g["router"]))) > 0

    def test_invalid_top_k(self):
        with pytest.raises(ValueError, match="top_k"):
            nn.MoE(4, 8, top_k=5)

    def test_state_structure_stable(self):
        # init-state and post-apply-state must match (checkpoint contract)
        layer = _moe()
        params, state, _ = layer.init(jax.random.PRNGKey(7), (8,))
        _, new_state = layer.apply(params, state, jnp.ones((4, 8)))
        assert jax.tree_util.tree_structure(state) == \
            jax.tree_util.tree_structure(new_state)


class TestMoETraining:
    # @slow (tier-1 budget, PR 17): ~8s convergence drive; MoE numerics
    # stay in-tier via TestExpertParallel::test_ep_matches_single_device
    # and the router/balance-loss units, and transformer-stack convergence
    # stays in-tier via TestTransformerTraining::test_learns_copy_task.
    @pytest.mark.slow
    def test_moe_transformer_learns(self):
        VOCAB = 32
        rng = np.random.default_rng(2)
        starts = rng.integers(0, VOCAB, size=128)
        toks = (starts[:, None] + np.arange(17)[None]) % VOCAB
        x, y = toks[:, :-1].astype(np.int32), toks[:, 1:].astype(np.int32)
        model = dtpu.Model(dtpu.models.transformer_lm(
            VOCAB, num_layers=2, d_model=32, num_heads=2, max_len=16,
            moe_experts=4, moe_every=2))
        model.compile(optimizer=dtpu.optim.Adam(1e-2),
                      loss="sparse_categorical_crossentropy",
                      metrics=["accuracy"])
        hist = model.fit(x, y, batch_size=64, epochs=8, verbose=0, seed=9)
        assert hist.history["accuracy"][-1] > 0.5, hist.history


class TestExpertParallel:
    def test_padded_eval_matches_exact_eval(self):
        """VERDICT r4 weak #6: evaluate()'s padded final batch used to
        feed pad ROWS into MoE routing — consuming expert capacity and
        biasing the load-balance aux loss. With eval_sample_weights, a
        padded evaluation must match the exact-batch one."""
        rng = np.random.default_rng(0)
        x = rng.integers(0, 32, (5, 16)).astype(np.int32)
        y = rng.integers(0, 32, (5, 16)).astype(np.int32)

        # capacity_factor=4 (never binds): capacity quantizes with the
        # token count, so a binding capacity would differ between the
        # padded and exact shapes for reasons unrelated to pad leakage.
        m = dtpu.Model(nn.Sequential([
            nn.Embedding(32, 16),
            nn.MoE(4, 32, capacity_factor=4.0),
            nn.Dense(32),
        ]))
        m.compile(optimizer=dtpu.optim.SGD(0.1),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
        m.build((16,))
        # batch_size=8 pads the 5-row batch; batch_size=5 is exact.
        padded = m.evaluate(x, y, batch_size=8, verbose=0)
        exact = m.evaluate(x, y, batch_size=5, verbose=0)
        assert padded["accuracy"] == pytest.approx(exact["accuracy"],
                                                   abs=1e-6)
        assert padded["loss"] == pytest.approx(exact["loss"], rel=1e-5)

    def test_eval_sample_weights_zero_rows_do_not_route(self):
        """Zero-weighted rows must not consume expert capacity. The zero
        rows come FIRST and capacity binds hard (top_k=1, cap=3 per
        expert vs 12 dead + 12 valid tokens): without the exclusion the
        dead rows win the cumsum dispatch priority and starve the valid
        ones. (Exact-output comparison against an unpadded run is not
        possible when capacity binds — cap quantizes with the padded
        group size — so the assertion is displacement itself.)"""
        from distributed_tpu.nn.core import eval_sample_weights

        layer = _moe(e=2, h=8, top_k=1, capacity_factor=0.25)
        params, state, _ = layer.init(jax.random.PRNGKey(0), (4, 8))
        x = jax.random.normal(jax.random.PRNGKey(1), (6, 4, 8))
        w = jnp.array([0, 0, 0, 1, 1, 1], jnp.float32)
        cap = layer._capacity(layer._group_size(24))
        assert cap == 3  # capacity genuinely binds

        out_plain, _ = layer.apply(params, state, x, train=False)
        with eval_sample_weights(w):
            out_w, st_w = layer.apply(params, state, x, train=False)
        routed_plain = (np.abs(np.asarray(out_plain[3:]))
                        .max(axis=-1) > 1e-6).sum()
        routed_w = (np.abs(np.asarray(out_w[3:]))
                    .max(axis=-1) > 1e-6).sum()
        # Unweighted: the 12 dead rows seize nearly all 2x3 slots (1 of
        # 12 valid tokens routes with this seed). Weighted: the valid
        # rows fill EVERY slot — 2 experts x cap 3 = 6 routed tokens.
        assert routed_plain <= 2, routed_plain
        assert routed_w == 2 * cap, routed_w
        # Aux statistics (pre-capacity router stats over valid tokens
        # only) match the exact unpadded run bit-for-bit.
        _, st_ref = layer.apply(params, state, x[3:], train=False)
        np.testing.assert_allclose(float(st_w["aux_loss"]),
                                   float(st_ref["aux_loss"]), rtol=1e-6)

    def test_expert_stack_sharded(self, devices):
        strategy = dtpu.DataExpertParallel(expert_parallel=4)
        with strategy.scope():
            model = dtpu.Model(dtpu.models.transformer_lm(
                32, num_layers=2, d_model=32, num_heads=2, max_len=16,
                moe_experts=4, moe_every=2))
            model.compile(optimizer=dtpu.optim.Adam(1e-2),
                          loss="sparse_categorical_crossentropy")
        model.build((16,))
        moe_params = model.params["residual_3"]["main"]["moe"]
        w_in = moe_params["w_in"]
        assert w_in.sharding.spec == PartitionSpec("expert", None, None)
        # physically one expert per shard on the 4-way axis
        shard_shapes = {s.data.shape for s in w_in.addressable_shards}
        assert shard_shapes == {(1,) + w_in.shape[1:]}
        # dense params stay replicated
        emb = model.params["embedding"]["table"]
        assert emb.sharding.spec == PartitionSpec()

    # @slow (tier-1 budget, PR 17): ~11s EP train parity; expert-stack
    # sharding, padded-vs-exact eval, and zero-row routing stay in-tier,
    # and the MoE layer unit tests pin the routing math.
    @pytest.mark.slow
    def test_ep_matches_single_device(self, devices):
        VOCAB = 32
        rng = np.random.default_rng(3)
        starts = rng.integers(0, VOCAB, size=64)
        toks = (starts[:, None] + np.arange(17)[None]) % VOCAB
        x, y = toks[:, :-1].astype(np.int32), toks[:, 1:].astype(np.int32)

        def train(strategy):
            def mk():
                m = dtpu.Model(dtpu.models.transformer_lm(
                    VOCAB, num_layers=2, d_model=32, num_heads=2, max_len=16,
                    moe_experts=4, moe_every=2))
                m.compile(optimizer=dtpu.optim.SGD(0.1),
                          loss="sparse_categorical_crossentropy")
                return m

            model = mk() if strategy is None else None
            if model is None:
                with strategy.scope():
                    model = mk()
            hist = model.fit(x, y, batch_size=32, epochs=2, verbose=0,
                             seed=6, shuffle=False)
            return hist.history["loss"]

        ref = train(None)
        ep = train(dtpu.DataExpertParallel(expert_parallel=4))
        np.testing.assert_allclose(ref, ep, rtol=2e-4, atol=2e-5)
