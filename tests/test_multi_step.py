"""compile(steps_per_execution=K): multi-step fused train execution.

One jitted dispatch runs K optimizer steps as a lax.scan over a
[K, batch, ...] super-batch, with loss/metric sums accumulated on device
and params/state/opt_state donated across the whole dispatch. These tests
pin numerical parity with the K=1 loop (same batch order, same per-step
RNG fold), composition with the other compile levers, and the K-step
granularity contract for callbacks/checkpoint resume. The capability it
exists for — amortizing per-step host dispatch overhead — is measured by
``bench.py multistep`` (docs/PERF.md "Multi-step execution").
"""

import numpy as np
import pytest

import jax
import distributed_tpu as dtpu
from distributed_tpu.training.callbacks import ModelCheckpoint


def small_data(n=512, seed=0):
    x, y = dtpu.data.synthetic_images(n, (28, 28), 10, seed)
    return x[..., None].astype(np.float32) / 255.0, y.astype(np.int32)


def make_model(K=None, momentum=0.0):
    m = dtpu.Model(dtpu.models.mnist_cnn())
    m.compile(
        optimizer=dtpu.optim.SGD(0.05, momentum=momentum),
        loss="sparse_categorical_crossentropy",
        metrics=["accuracy"],
        steps_per_execution=K,
    )
    return m


def assert_params_close(a, b, rtol=2e-5, atol=2e-6):
    for p, q in zip(jax.tree_util.tree_leaves(a.params),
                    jax.tree_util.tree_leaves(b.params)):
        np.testing.assert_allclose(np.asarray(p), np.asarray(q),
                                   rtol=rtol, atol=atol)


@pytest.mark.smoke
def test_k8_matches_k1_losses_and_params():
    """Acceptance parity: K=8 matches K=1 losses and params to fp32
    tolerance over 16 steps (2 epochs x 8), same shuffled batch order."""
    x, y = small_data()
    a, b = make_model(None), make_model(8)
    ha = a.fit(x, y, batch_size=32, epochs=2, steps_per_epoch=8,
               verbose=0, seed=0)
    hb = b.fit(x, y, batch_size=32, epochs=2, steps_per_epoch=8,
               verbose=0, seed=0)
    np.testing.assert_allclose(ha.history["loss"], hb.history["loss"],
                               rtol=1e-5)
    np.testing.assert_allclose(
        ha.history["accuracy"], hb.history["accuracy"], rtol=1e-5
    )
    assert a.step == b.step == 16
    assert_params_close(a, b)


def test_epoch_tail_shorter_than_k():
    """steps_per_epoch not divisible by K: the tail runs as a smaller
    final dispatch — every batch trains exactly once, in order."""
    x, y = small_data()
    a, b = make_model(None), make_model(4)
    ha = a.fit(x, y, batch_size=32, epochs=2, steps_per_epoch=5,
               verbose=0, seed=0)
    hb = b.fit(x, y, batch_size=32, epochs=2, steps_per_epoch=5,
               verbose=0, seed=0)
    assert b.step == 10
    np.testing.assert_allclose(ha.history["loss"], hb.history["loss"],
                               rtol=1e-5)
    assert_params_close(a, b)


def test_k_larger_than_epoch():
    """K > steps_per_epoch degrades to one whole-epoch dispatch."""
    x, y = small_data(n=128)
    m = make_model(32)
    h = m.fit(x, y, batch_size=32, epochs=2, steps_per_epoch=3, verbose=0,
              seed=0)
    assert m.step == 6
    assert np.isfinite(h.history["loss"]).all()


# @slow (tier-1 budget, PR 12): 9s composition matrix — each mechanism
# keeps its own in-tier pin (K8==K1 above, chunked head in
# test_chunked_head, grad_accum in test_zero, clip in test_fit).
@pytest.mark.slow
def test_composes_with_head_chunks_accumulation_and_clip():
    """steps_per_execution x head_chunks x gradient_accumulation_steps x
    grad_clip: the scanned body is the SAME chunked step the K=1 path
    jits, and the MultiSteps accumulator rides the opt_state through the
    scan carry — the composed run matches the unfused composed run."""
    rng = np.random.default_rng(0)
    x = rng.integers(0, 64, (16, 32)).astype(np.int32)
    y = rng.integers(0, 64, (16, 32)).astype(np.int32)

    def make(K):
        m = dtpu.Model(dtpu.models.transformer_lm(
            64, num_layers=2, d_model=16, num_heads=2, max_len=32))
        m.compile(optimizer=dtpu.optim.SGD(0.1),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"], grad_clip=1.0,
                  gradient_accumulation_steps=2, head_chunks=4,
                  steps_per_execution=K)
        m.build((32,))
        return m

    a, b = make(None), make(4)
    ha = a.fit(x, y, batch_size=8, epochs=2, verbose=0, seed=0)
    hb = b.fit(x, y, batch_size=8, epochs=2, verbose=0, seed=0)
    np.testing.assert_allclose(ha.history["loss"], hb.history["loss"],
                               rtol=1e-5)
    assert_params_close(a, b, rtol=2e-4, atol=2e-5)


# @slow (tier-1 budget, PR 17): ~5s composition cross-product; K under
# single device stays in-tier (test_k8_matches_k1_losses_and_params) and
# DP/pipeline numerics in their own suites — product only here.
@pytest.mark.slow
def test_under_data_parallel_with_pipeline(devices):
    """The stacked super-batch shards (None, 'data') under DP — K
    replicated, rows sharded — and fit(pipeline) collates through
    Pipeline.next_k. Parity with the K=1 pipeline run, and replicas stay
    bit-identical (the fused all-reduce runs inside the scan)."""
    x, y = dtpu.data.synthetic_images(512, (28, 28), 10, seed=2)

    def make(K):
        with dtpu.DataParallel().scope():
            m = dtpu.Model(dtpu.models.mnist_cnn())
            m.compile(optimizer=dtpu.optim.Adam(1e-3),
                      loss="sparse_categorical_crossentropy",
                      metrics=["accuracy"], steps_per_execution=K)
        return m

    a, b = make(None), make(4)
    ha = a.fit(dtpu.data.Pipeline(x[..., None], y, 64, seed=0), epochs=1,
               verbose=0)
    hb = b.fit(dtpu.data.Pipeline(x[..., None], y, 64, seed=0), epochs=1,
               verbose=0)
    np.testing.assert_allclose(ha.history["loss"], hb.history["loss"],
                               rtol=1e-5)
    assert_params_close(a, b, rtol=2e-4, atol=2e-5)
    for leaf in jax.tree_util.tree_leaves(b.params):
        shards = [np.asarray(s.data) for s in leaf.addressable_shards]
        for s in shards[1:]:
            np.testing.assert_array_equal(shards[0], s)


def test_stacked_put_batch_sharding(devices):
    """put_batch(stacked=True) shards dim 1 (the batch rows), replicating
    the leading K dim, for the DataParallel family."""
    strat = dtpu.DataParallel()
    arr = np.zeros((4, 16, 3), np.float32)
    placed = strat.put_batch({"x": arr}, stacked=True)["x"]
    assert placed.shape == (4, 16, 3)
    spec = placed.sharding.spec
    assert spec[0] is None and spec[1] == "data", spec
    # Single shard holds all K steps of its row slice.
    assert placed.addressable_shards[0].data.shape == (4, 2, 3)


# @slow (tier-1 budget, PR 17): ~9s resume drive; K-aligned cursor math
# stays in-tier via the epoch/tail schedule units, and checkpoint-resume
# under chunking stays in-tier via test_chunked_head_checkpoint_resume
# (the K x save_freq boundary matrix is already @slow per PR 15).
@pytest.mark.slow
def test_checkpoint_resume_k_aligned(tmp_path):
    """ModelCheckpoint resume under K: the restored cursor is K-aligned
    (every dispatch advances K full steps), and the resumed run replays
    no batch — bit-identical to an uninterrupted run, momentum included."""
    x, y = small_data()
    ref = make_model(4, momentum=0.9)
    ref.fit(x, y, batch_size=64, epochs=3, steps_per_epoch=4, verbose=0,
            seed=3)

    m1 = make_model(4, momentum=0.9)
    m1.fit(x, y, batch_size=64, epochs=2, steps_per_epoch=4, verbose=0,
           seed=3, callbacks=[ModelCheckpoint(tmp_path, save_freq="epoch")])
    m2 = make_model(4, momentum=0.9)
    ck = ModelCheckpoint(tmp_path, save_freq="epoch", restore=True)
    m2.fit(x, y, batch_size=64, epochs=3, steps_per_epoch=4, verbose=0,
           seed=3, callbacks=[ck])
    assert ck.ckpt.all_steps()[-1] % 4 == 0  # saves land on K boundaries
    assert m2.step == 12
    for p, q in zip(jax.tree_util.tree_leaves(ref.params),
                    jax.tree_util.tree_leaves(m2.params)):
        np.testing.assert_array_equal(np.asarray(p), np.asarray(q))


def test_int_save_freq_crosses_boundaries(tmp_path):
    """An int save_freq fires when the K-strided step counter CROSSES a
    boundary (step % freq == 0 may never be observed under K-jumps), and
    the saved steps are K-aligned."""
    x, y = small_data(n=128)
    ck = ModelCheckpoint(tmp_path, save_freq=6)
    m = make_model(4)
    m.fit(x, y, batch_size=32, epochs=1, steps_per_epoch=16, verbose=0,
          seed=0, callbacks=[ck])  # dispatches end at steps 4, 8, 12, 16
    saved = ck.ckpt.all_steps()
    assert saved == [8, 12], saved  # crossings of 6 and 12
    assert all(s % 4 == 0 for s in saved)


# @slow (tier-1 budget, PR 12): 9s tail x save_freq edge matrix;
# boundary-crossing saves and K-aligned resume each keep their own
# in-tier tests (test_int_save_freq_crosses_boundaries,
# test_checkpoint_resume_k_aligned).
@pytest.mark.slow
def test_tail_dispatch_with_save_freq_inside_it(tmp_path):
    """next_k tail behavior x checkpointing: steps_per_epoch=10 with K=4
    runs dispatches of 4, 4, 2 — the save_freq=5 boundary falls INSIDE
    fused dispatches both times (at raw steps 5 and 15), so saves must
    land at the K-strided crossings (8, 10->no: boundary 10 is crossed at
    the tail dispatch, 18 at the second epoch's mid dispatch, 20 at its
    tail), each checkpoint complete and restorable."""
    x, y = small_data(n=512)
    ck = ModelCheckpoint(tmp_path, save_freq=5, keep=10)
    m = make_model(4, momentum=0.9)
    m.fit(x, y, batch_size=32, epochs=2, steps_per_epoch=10, verbose=0,
          seed=0, callbacks=[ck])
    # Dispatch ends: 4, 8, 10 | 14, 18, 20. save_freq=5 buckets crossed
    # at 8 (bucket 1), 10 (2), 18 (3), 20 (4) — never at a raw multiple
    # of 5, because 5 and 15 sit inside fused dispatches.
    assert ck.ckpt.all_steps() == [8, 10, 18, 20]
    # The tail-boundary checkpoint restores into a bit-exact resume.
    ref = make_model(4, momentum=0.9)
    ref.fit(x, y, batch_size=32, epochs=2, steps_per_epoch=10, verbose=0,
            seed=0)
    resumed = make_model(4, momentum=0.9)
    ck.ckpt.restore_into(resumed, step=10)
    resumed.fit(x, y, batch_size=32, epochs=2, steps_per_epoch=10,
                verbose=0, seed=0, initial_epoch=1)
    for p, q in zip(jax.tree_util.tree_leaves(ref.params),
                    jax.tree_util.tree_leaves(resumed.params)):
        np.testing.assert_array_equal(np.asarray(p), np.asarray(q))


def test_tail_smaller_than_k_via_pipeline_next_k():
    """Pipeline.next_k serves the tail collation too: an epoch of 6 steps
    at K=4 pulls next_k(4) then next_k(2), and the pipeline cursor lands
    exactly at the epoch boundary (no over-read)."""
    x, y = dtpu.data.synthetic_images(256, (28, 28), 10, seed=4)
    p = dtpu.data.Pipeline(x[..., None], y, 32, seed=9, use_native=False)
    m = make_model(4)
    m.fit(p, epochs=1, steps_per_epoch=6, verbose=0)
    assert m.step == 6
    assert p.steps_emitted == 6
    p.close()


def test_callbacks_observe_monotonic_k_strided_step():
    x, y = small_data(n=256)
    seen = []
    cb = dtpu.callbacks.LambdaCallback(
        on_batch_end=lambda model, step, logs: seen.append(
            (step, model.step, float(np.asarray(logs["loss"])))
        )
    )
    m = make_model(4)
    m.fit(x, y, batch_size=32, epochs=1, steps_per_epoch=8, verbose=0,
          seed=0, callbacks=[cb])
    steps = [s for s, _, _ in seen]
    assert steps == [4, 8]
    assert all(s == ms for s, ms, _ in seen)  # step arg == model.step
    # The per-dispatch loss is the K-step mean — a finite scalar.
    assert all(np.isfinite(l) for _, _, l in seen)


def test_progress_line_at_k_granularity(capsys):
    """verbose=1 with K: the bar advances K steps per update and still
    lands on total/total at epoch end."""
    x, y = small_data(n=128)
    m = make_model(4)
    m.fit(x, y, batch_size=32, epochs=1, verbose=1, seed=0)
    out = capsys.readouterr().out
    assert "4/4" in out and "ETA" in out


def test_steps_per_execution_validation():
    m = dtpu.Model(dtpu.models.mnist_cnn())
    for bad in (0, -2, 2.5):
        with pytest.raises(ValueError, match="steps_per_execution"):
            m.compile(optimizer="sgd",
                      loss="sparse_categorical_crossentropy",
                      steps_per_execution=bad)
    # K=1 is the plain path, accepted and inert.
    m.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
              steps_per_execution=1)
    assert m.steps_per_execution == 1


def test_pipeline_next_k_matches_sequential_batches():
    """Pipeline.next_k(k) emits exactly the k batches k __next__ calls
    would, stacked, advancing the same cursor — on both the native and
    the pure-Python implementation."""
    x, y = dtpu.data.synthetic_images(256, (28, 28), 10, seed=5)
    for use_native in (None, False):
        kw = dict(batch_size=32, seed=7, shuffle=True,
                  use_native=use_native)
        a = dtpu.data.Pipeline(x[..., None], y, **kw)
        b = dtpu.data.Pipeline(x[..., None], y, **kw)
        xs, ys = a.next_k(3)
        assert xs.shape == (3, 32, 28, 28, 1) and ys.shape == (3, 32)
        for i in range(3):
            xb, yb = next(b)
            np.testing.assert_array_equal(xs[i], xb)
            np.testing.assert_array_equal(ys[i], yb)
        assert a.steps_emitted == 3
        # The cursor continues past the collated block.
        xa, _ = next(a)
        xb, _ = next(b)
        np.testing.assert_array_equal(xa, xb)
        with pytest.raises(ValueError, match="k >= 1"):
            a.next_k(0)


def test_step_timer_multi_step_tick():
    """StepTimer.tick(steps=K) counts K steps per fused dispatch so
    steps_per_sec reports per-step throughput; the single-step contract
    (warmup excluded) is unchanged."""
    import time

    from distributed_tpu.utils.profiler import StepTimer

    t = StepTimer(warmup=1)
    t.tick()            # warmup step: closes the window, starts the clock
    t.tick(steps=8)
    t.tick(steps=8)
    time.sleep(0.01)
    assert t.steps == 17
    rate = t.steps_per_sec
    assert rate > 0
    # 16 counted steps over >= 10ms: bounded above by 16 / 0.01.
    assert rate <= 16 / 0.01

    # A K-jump that lands past the warmup boundary starts the clock there.
    t2 = StepTimer(warmup=4)
    t2.tick(steps=8)
    assert t2._t0 is not None and t2.steps == 8
    assert t2.steps_per_sec == 0.0  # nothing counted yet
    t2.tick(steps=8)
    assert t2.steps_per_sec > 0


def test_predict_async_window_matches_blocking():
    """predict() keeps outputs on device behind a sliding fetch window;
    results are identical to per-batch fetching, including the padded
    remainder, and across window-boundary-sized inputs."""
    x, y = small_data(n=100)
    m = make_model(None)
    m.build((28, 28, 1))
    # 100 rows at batch 4 = 25 batches > the 16-batch window: exercises
    # the mid-loop drain, the final drain, and the padded last batch.
    preds = m.predict(x, batch_size=4)
    assert preds.shape == (100, 10)
    np.testing.assert_allclose(preds, m.predict(x, batch_size=64),
                               rtol=1e-5, atol=1e-5)


def test_predict_window_wrap_preserves_row_order():
    """Ordering regression for the sliding-window drain: when the batch
    count wraps past the 16-batch window (mid-loop pops interleave with
    fresh dispatches, then the tail drains in one batched wait), every
    output row must still correspond to ITS input row. Rows are made
    distinguishable by comparing against per-row single-batch predicts at
    window-straddling positions."""
    x, _ = small_data(n=18 * 4 + 2)  # 19 batches at batch 4: wraps + pad
    m = make_model(None)
    m.build((28, 28, 1))
    preds = m.predict(x, batch_size=4)
    assert preds.shape == (74, 10)
    # Spot rows on both sides of the window boundary (batch 15/16/18) and
    # inside the padded tail batch.
    for row in (0, 59, 63, 65, 72, 73):
        np.testing.assert_allclose(
            preds[row], m.predict(x[row:row + 1], batch_size=1)[0],
            rtol=1e-5, atol=1e-5,
        )
