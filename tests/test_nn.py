"""Layer-level tests: shape inference, parameter counts, forward semantics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tpu import nn
from distributed_tpu.models import mnist_cnn
from distributed_tpu.utils.tree import tree_size


def test_mnist_cnn_param_count_matches_reference():
    # 347,146 params / 6 tensors — BASELINE.md model-size row, derived from
    # /root/reference/README.md:292-298.
    model = mnist_cnn()
    params, state, out = model.init(jax.random.PRNGKey(0), (28, 28, 1))
    assert out == (10,)
    assert tree_size(params) == 347_146
    assert len(jax.tree_util.tree_leaves(params)) == 6
    assert state == {}


def test_sequential_shapes_and_forward():
    model = mnist_cnn()
    params, state, _ = model.init(jax.random.PRNGKey(0), (28, 28, 1))
    x = jnp.ones((4, 28, 28, 1))
    y, _ = model.apply(params, state, x)
    assert y.shape == (4, 10)
    assert jnp.isfinite(y).all()


def test_conv2d_same_and_stride():
    layer = nn.Conv2D(8, 3, strides=2, padding="same")
    params, _, out = layer.init(jax.random.PRNGKey(0), (28, 28, 3))
    assert out == (14, 14, 8)
    y, _ = layer.apply(params, {}, jnp.ones((2, 28, 28, 3)))
    assert y.shape == (2, 14, 14, 8)


def test_dense_on_sequence_input():
    layer = nn.Dense(16)
    params, _, out = layer.init(jax.random.PRNGKey(0), (12, 8))
    assert out == (12, 16)
    y, _ = layer.apply(params, {}, jnp.ones((2, 12, 8)))
    assert y.shape == (2, 12, 16)


def test_pooling():
    mp = nn.MaxPool2D(2)
    _, _, out = mp.init(jax.random.PRNGKey(0), (28, 28, 4))
    assert out == (14, 14, 4)
    x = jnp.arange(16.0).reshape(1, 4, 4, 1)
    y, _ = mp.apply({}, {}, x)
    assert y[0, 0, 0, 0] == 5.0  # max of [[0,1],[4,5]]
    ap = nn.AvgPool2D(2)
    y, _ = ap.apply({}, {}, x)
    assert y[0, 0, 0, 0] == 2.5


@pytest.mark.smoke
def test_batchnorm_train_vs_eval():
    bn = nn.BatchNorm(momentum=0.5)
    params, state, _ = bn.init(jax.random.PRNGKey(0), (8,))
    x = jax.random.normal(jax.random.PRNGKey(1), (64, 8)) * 3 + 2
    y, new_state = bn.apply(params, state, x, train=True)
    # Normalized output: ~zero mean, ~unit var.
    assert jnp.abs(jnp.mean(y)) < 1e-4
    assert jnp.abs(jnp.std(y) - 1.0) < 1e-2
    # Running stats moved toward batch stats.
    assert jnp.all(new_state["mean"] != state["mean"])
    # Eval path uses running stats and returns no state update.
    y2, s2 = bn.apply(params, new_state, x, train=False)
    assert s2 == {}


def test_dropout_train_and_inference():
    do = nn.Dropout(0.5)
    x = jnp.ones((1000,))
    y, _ = do.apply({}, {}, x, train=False)
    assert (y == x).all()
    y, _ = do.apply({}, {}, x, train=True, rng=jax.random.PRNGKey(0))
    kept = float(jnp.mean((y > 0).astype(jnp.float32)))
    assert 0.4 < kept < 0.6
    assert jnp.allclose(y[y > 0], 2.0)
    with pytest.raises(ValueError):
        do.apply({}, {}, x, train=True)


def test_layer_auto_naming():
    model = nn.Sequential([nn.Dense(4), nn.Dense(4), nn.Conv2D(3, 1)])
    names = [l.name for l in model.layers]
    assert names == ["dense", "dense_1", "conv2d"]
    with pytest.raises(ValueError):
        nn.Sequential([nn.Dense(4, name="a"), nn.Dense(4, name="a")])


def test_embedding_and_layernorm():
    emb = nn.Embedding(100, 16)
    params, _, out = emb.init(jax.random.PRNGKey(0), (12,))
    assert out == (12, 16)
    tokens = jnp.array([[1, 2, 3]])
    y, _ = emb.apply(params, {}, tokens)
    assert y.shape == (1, 3, 16)
    ln = nn.LayerNorm()
    p, _, _ = ln.init(jax.random.PRNGKey(0), (16,))
    z, _ = ln.apply(p, {}, y)
    assert jnp.abs(jnp.mean(z)) < 1e-4


def test_batchnorm_stats_match_f32_reference():
    """The accumulating-reduction form (no materialized f32 activation
    copy) must produce the same f32 statistics as the naive cast-first
    computation, including on bf16 inputs."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    x = (rng.standard_normal((64, 7, 7, 32)) * 3 + 1.5).astype(np.float32)
    for dtype in (jnp.float32, jnp.bfloat16):
        xd = jnp.asarray(x, dtype)
        bn = nn.BatchNorm()
        params, state, _ = bn.init(jax.random.PRNGKey(0), (7, 7, 32))
        _, new_state = bn.apply(params, state, xd, train=True)
        xf = np.asarray(xd, np.float32)
        want_mean = xf.mean(axis=(0, 1, 2))
        want_var = xf.var(axis=(0, 1, 2))
        got_mean = (np.asarray(new_state["mean"]) - 0.9 * 0.0) / 0.1
        got_var = (np.asarray(new_state["var"]) - 0.9 * 1.0) / 0.1
        np.testing.assert_allclose(got_mean, want_mean, rtol=2e-3, atol=2e-3)
        np.testing.assert_allclose(got_var, want_var, rtol=2e-2, atol=2e-2)
        assert (got_var >= 0).all()


def test_batchnorm_running_shift_matches_data_shift():
    """stats_shift='running' is the epilogue-fusable conditioning variant
    (see nn.layers.BatchNorm.stats_shift); its statistics and outputs must
    match the data-shift default, including mid-training when the running
    mean is nonzero."""
    rng = np.random.default_rng(1)
    x = jnp.asarray((rng.standard_normal((32, 5, 5, 16)) * 2 + 4.0)
                    .astype(np.float32))
    warm_state = {"mean": jnp.asarray(rng.standard_normal(16), jnp.float32),
                  "var": jnp.abs(jnp.asarray(rng.standard_normal(16),
                                             jnp.float32)) + 0.5}
    for state0 in ({"mean": jnp.zeros(16), "var": jnp.ones(16)}, warm_state):
        outs = {}
        for shift in ("data", "running"):
            bn = nn.BatchNorm(stats_shift=shift)
            params, _, _ = bn.init(jax.random.PRNGKey(0), (5, 5, 16))
            y, new_state = bn.apply(params, state0, x, train=True)
            outs[shift] = (np.asarray(y), np.asarray(new_state["mean"]),
                           np.asarray(new_state["var"]))
        for a, b in zip(outs["data"], outs["running"]):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-4)


def test_space_to_depth_rearranges_blocks():
    s2d = nn.SpaceToDepth(2)
    _, _, out = s2d.init(jax.random.PRNGKey(0), (4, 6, 3))
    assert out == (2, 3, 12)
    x = jnp.arange(2 * 4 * 6 * 3, dtype=jnp.float32).reshape(2, 4, 6, 3)
    y, _ = s2d.apply({}, {}, x)
    assert y.shape == (2, 2, 3, 12)
    # block (0,0) of image 0 = rows 0-1, cols 0-1, channel-major within block
    want = np.concatenate(
        [np.asarray(x)[0, 0, 0], np.asarray(x)[0, 0, 1],
         np.asarray(x)[0, 1, 0], np.asarray(x)[0, 1, 1]])
    np.testing.assert_array_equal(np.asarray(y)[0, 0, 0], want)
    with pytest.raises(ValueError):
        nn.SpaceToDepth(2).init(jax.random.PRNGKey(0), (5, 6, 3))


# @slow (tier-1 budget, PR 10): 8s stem-variant training; the stem's
# structural checks and the resnet DP training test stay in-tier.
@pytest.mark.slow
def test_resnet_space_to_depth_stem_trains():
    import distributed_tpu as dtpu

    model = dtpu.Model(dtpu.models.resnet(
        50, 10, stem="space_to_depth", stage_blocks=(1, 1, 1, 1)))
    model.compile(optimizer=dtpu.optim.SGD(0.1),
                  loss="sparse_categorical_crossentropy")
    model.build((32, 32, 3))
    x = np.random.default_rng(0).standard_normal((4, 32, 32, 3)).astype(np.float32)
    y = np.array([0, 1, 2, 3], np.int32)
    hist = model.fit(x, y, batch_size=4, epochs=1, steps_per_epoch=1, verbose=0)
    assert np.isfinite(hist.history["loss"][0])
    with pytest.raises(ValueError):
        dtpu.models.resnet(50, 10, stem="nope")
