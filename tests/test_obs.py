"""Unified observability runtime (distributed_tpu/obs, docs/OBSERVABILITY.md).

Covers the four tentpole pieces in-process (registry, spans, flight
recorder, cross-rank aggregation), the exporters and the dtpu-events CLI,
the derived-view parity contract (``last_fit_telemetry`` /
``last_run_telemetry`` == the registry's stored reports, key-for-key with
the PR 13 key sets), and the PR's satellites: the event log's cached
append fd (rotation reopen + concurrent-writer whole-line interleaving),
``StepTimer.stall_report``'s unattributed remainder + per-category
fractions, and rank-stamped structured logging. The supervised-gang
straggler path runs for real in ``bench.py obs`` (and its schema smoke in
test_bench.py); here the aggregation math is pinned on synthetic event
streams and the supervisor's emission on a scripted launcher.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import distributed_tpu as dtpu  # noqa: E402
from distributed_tpu import obs  # noqa: E402
from distributed_tpu.obs import aggregate, cli, export  # noqa: E402
from distributed_tpu.obs.flight import FlightRecorder  # noqa: E402
from distributed_tpu.obs.registry import MetricsRegistry  # noqa: E402
from distributed_tpu.resilience import FaultInjector  # noqa: E402
from distributed_tpu.resilience.supervisor import (  # noqa: E402
    Supervisor,
    recovery_rows,
)
from distributed_tpu.launch.core import WorkerResult  # noqa: E402
from distributed_tpu.utils.events import EventLog, read_events  # noqa: E402
from distributed_tpu.utils.logging import rank_world  # noqa: E402
from distributed_tpu.utils.profiler import StepTimer  # noqa: E402


def small_model(width=16):
    m = dtpu.Model(dtpu.nn.Sequential([
        dtpu.nn.Flatten(),
        dtpu.nn.Dense(width, activation="relu"),
        dtpu.nn.Dense(10),
    ]))
    m.compile(optimizer=dtpu.optim.SGD(0.05),
              loss="sparse_categorical_crossentropy")
    return m


# ------------------------------------------------------------- registry ----
class TestRegistry:
    def test_counters_gauges_histograms(self):
        reg = MetricsRegistry()
        reg.counter("a", 2)
        reg.counter("a", 3)
        reg.gauge("g", 1.5)
        reg.gauge("g", 2.5)  # last-value-wins
        reg.observe("h", 0.003)
        reg.observe("h", 999.0)  # overflow bucket
        snap = reg.snapshot()
        assert snap["counters"]["a"] == 5.0
        assert snap["gauges"]["g"] == 2.5
        h = snap["histograms"]["h"]
        assert h["count"] == 2 and h["overflow"] == 1
        assert sum(h["counts"]) == 1
        assert h["sum"] == pytest.approx(999.003)

    def test_ring_is_bounded(self):
        reg = MetricsRegistry(ring_size=8)
        for i in range(50):
            reg.ring_append("r", {"i": i})
        ring = reg.ring("r")
        assert len(ring) == 8  # never grows past N
        assert [r["i"] for r in ring] == list(range(42, 50))  # newest kept

    def test_snapshot_deterministic(self):
        """Same operations -> same key sequence AND same JSON (modulo the
        timestamp): the determinism exporters and tests rely on."""
        def build():
            reg = MetricsRegistry()
            for name in ("z", "a", "m"):
                reg.counter(name)
                reg.gauge("g/" + name, 1)
                reg.observe("h/" + name, 0.01)
                reg.ring_append("r/" + name, {"v": 1})
            return reg.snapshot()

        s1, s2 = build(), build()
        s1.pop("ts"), s2.pop("ts")
        assert json.dumps(s1) == json.dumps(s2)
        assert list(s1["counters"]) == ["a", "m", "z"]  # sorted

    def test_disabled_registry_noops(self):
        reg = MetricsRegistry()
        prev = obs.set_enabled(False)
        try:
            reg.counter("c")
            reg.gauge("g", 1)
            reg.observe("h", 1.0)
            reg.ring_append("r", {"x": 1})
            snap = reg.snapshot()
            assert not snap["counters"] and not snap["gauges"]
            assert not snap["histograms"] and not snap["rings"]
            # Reports STILL store: legacy telemetry must survive obs-off.
            rep = reg.set_report("x", {"k": 1})
            assert reg.get_report("x") is rep
        finally:
            obs.set_enabled(prev)

    def test_set_report_returns_stored_object(self):
        reg = MetricsRegistry()
        d = {"a": 1}
        assert reg.set_report("view", d) is d
        assert reg.get_report("view") is d


# ---------------------------------------------------------------- spans ----
class TestSpans:
    def test_span_records_and_nests(self):
        reg = MetricsRegistry()
        with obs.span("outer", registry=reg):
            assert obs.current_span() == "outer"
            with obs.span("inner", registry=reg):
                assert obs.current_span() == "outer/inner"
                time.sleep(0.01)
        assert obs.current_span() is None
        snap = reg.snapshot()
        assert snap["counters"]["span_calls/outer"] == 1
        assert snap["counters"]["span_calls/outer/inner"] == 1
        assert snap["histograms"]["span_seconds/outer/inner"]["sum"] >= 0.01

    def test_span_attributes_into_timer(self):
        t = StepTimer(warmup=0)
        with obs.span("input_wait", timer=t):
            time.sleep(0.005)
        assert t.stalls["input_wait"] >= 0.005

    def test_span_handle_exposes_seconds(self):
        with obs.span("x") as sp:
            time.sleep(0.002)
        assert sp.seconds >= 0.002

    def test_disabled_span_still_times_for_timer(self):
        """obs-off: the legacy stall buckets must be unchanged (the bench's
        bare half still reports input_stall_fraction etc.)."""
        reg = MetricsRegistry()
        t = StepTimer(warmup=0)
        prev = obs.set_enabled(False)
        try:
            with obs.span("dispatch", timer=t, registry=reg):
                time.sleep(0.002)
        finally:
            obs.set_enabled(prev)
        assert t.stalls["dispatch"] >= 0.002
        assert not reg.snapshot()["histograms"]

    def test_stall_attribute_forwards_to_registry(self):
        reg = obs.default_registry()
        before = reg.counter_value("stall_seconds/custom_cat")
        t = StepTimer(warmup=0)
        t.attribute("custom_cat", 0.5)
        assert reg.counter_value("stall_seconds/custom_cat") == \
            pytest.approx(before + 0.5)


# ------------------------------------------------------------ stall report --
class TestStallReport:
    def test_unattributed_and_fractions(self):
        t = StepTimer(warmup=0)
        t.attribute("input_wait", 0.01)
        t.attribute("dispatch", 0.02)
        time.sleep(0.03)
        rep = t.stall_report()
        # Legacy keys intact:
        assert {"input_wait", "dispatch", "checkpoint_wait",
                "total_seconds", "input_stall_fraction"} <= set(rep)
        # New: the honest remainder + per-category fractions.
        assert rep["unattributed"] >= 0.0
        assert rep["unattributed"] == pytest.approx(
            rep["total_seconds"] - rep["input_wait"] - rep["dispatch"]
            - rep["checkpoint_wait"], abs=1e-4)
        for cat in ("input_wait", "dispatch", "checkpoint_wait",
                    "unattributed"):
            frac = rep[f"{cat}_fraction"]
            assert 0.0 <= frac <= 1.0
        assert rep["input_stall_fraction"] == rep["input_wait_fraction"]

    def test_custom_category_gets_fraction(self):
        t = StepTimer(warmup=0)
        t.attribute("prefill", 0.004)
        rep = t.stall_report()
        assert rep["prefill"] >= 0.004
        assert "prefill_fraction" in rep


# ------------------------------------------------------- flight recorder ----
class TestFlightRecorder:
    def test_ring_never_grows_past_capacity(self):
        rec = FlightRecorder(capacity=16)
        for i in range(100):
            rec.record("step", step=i)
        assert len(rec) == 16
        steps = [r["step"] for r in rec.snapshot()]
        assert steps == list(range(84, 100))

    def test_dump_writes_header_and_records(self, tmp_path, monkeypatch):
        monkeypatch.setenv("DTPU_EVENT_LOG", str(tmp_path / "ev.jsonl"))
        rec = FlightRecorder(capacity=8)
        for i in range(5):
            rec.record("step", step=i)
        path = rec.dump(tmp_path / "dump.jsonl", reason="test")
        records = obs.flight.read_dump(path)
        header = records[0]
        assert header["kind"] == "flight_header"
        assert header["reason"] == "test" and header["records"] == 5
        assert [r["step"] for r in records[1:]] == list(range(5))
        # The dump emitted a flight_dump event into the ambient log.
        events = read_events(tmp_path / "ev.jsonl")
        fd = [e for e in events if e["event"] == "flight_dump"]
        assert len(fd) == 1 and fd[0]["path"] == str(path)
        assert fd[0]["records"] == 5

    def test_dump_torn_final_line_recovers(self, tmp_path):
        rec = FlightRecorder()
        for i in range(3):
            rec.record("step", step=i)
        path = rec.dump(tmp_path / "dump.jsonl", reason="torn")
        with open(path, "a") as f:
            f.write('{"kind": "step", "step": 99')  # writer died mid-append
        records = obs.flight.read_dump(path)
        assert [r.get("step") for r in records[1:]] == [0, 1, 2]

    def test_dump_without_location_is_noop(self, monkeypatch):
        monkeypatch.delenv("DTPU_FLIGHT_DIR", raising=False)
        monkeypatch.delenv("DTPU_EVENT_LOG", raising=False)
        assert FlightRecorder().dump(reason="nowhere") is None

    def test_record_noop_when_disabled(self):
        rec = FlightRecorder()
        prev = obs.set_enabled(False)
        try:
            rec.record("step", step=1)
        finally:
            obs.set_enabled(prev)
        assert len(rec) == 0

    def test_fit_records_steps_and_exception_dumps(self, tmp_path,
                                                   monkeypatch):
        monkeypatch.setenv("DTPU_FLIGHT_DIR", str(tmp_path))
        x, y = dtpu.data.synthetic_images(64, (8, 8), 10, 0)
        m = small_model()
        before = len(obs.default_recorder())
        m.fit(x, y, batch_size=16, epochs=1, steps_per_epoch=3, verbose=0)
        assert len(obs.default_recorder()) >= min(
            before + 3, obs.default_recorder().capacity
        )
        recs = obs.default_recorder().snapshot()
        step_recs = [r for r in recs if r["kind"] == "step"]
        assert {"step", "seconds", "input_wait_s", "dispatch_s",
                "self_s"} <= set(step_recs[-1])

        class Boom(Exception):
            pass

        class Bomb(dtpu.callbacks.Callback):
            def on_batch_end(self, model, step, logs):
                raise Boom("kaboom")

        with pytest.raises(Boom):
            m.fit(x, y, batch_size=16, epochs=1, steps_per_epoch=3,
                  verbose=0, callbacks=[Bomb()])
        dumps = list(tmp_path.glob("flight-rank*.jsonl"))
        assert dumps, "unhandled fit exception must leave a flight dump"
        header = obs.flight.read_dump(dumps[0])[0]
        assert header["reason"] == "exception:Boom"


# -------------------------------------------------------------- event log ---
class TestEventLogFd:
    def test_cached_fd_appends_whole_records(self, tmp_path):
        log = EventLog(tmp_path / "ev.jsonl")
        for i in range(5):
            log.emit("tick", i=i)
        assert log._f is not None  # handle cached, not reopened per emit
        assert [e["i"] for e in log.read()] == list(range(5))
        log.close()

    def test_reopen_after_rotation(self, tmp_path):
        path = tmp_path / "ev.jsonl"
        log = EventLog(path)
        log.emit("a")
        os.rename(path, tmp_path / "ev.jsonl.1")
        log.emit("b")  # ENOENT at the configured path -> reopen
        assert [e["event"] for e in read_events(path)] == ["b"]
        assert [e["event"] for e in read_events(tmp_path / "ev.jsonl.1")] \
            == ["a"]
        log.close()

    def test_reopen_after_unlink(self, tmp_path):
        path = tmp_path / "ev.jsonl"
        log = EventLog(path)
        log.emit("a")
        os.unlink(path)
        log.emit("b")
        assert [e["event"] for e in read_events(path)] == ["b"]
        log.close()

    def test_concurrent_writers_interleave_whole_lines(self, tmp_path):
        """Two PROCESSES appending concurrently produce only whole,
        parseable lines (O_APPEND + one write per record)."""
        path = tmp_path / "ev.jsonl"
        script = (
            "import sys\n"
            "sys.path.insert(0, sys.argv[3])\n"
            "from distributed_tpu.utils.events import EventLog\n"
            "log = EventLog(sys.argv[1])\n"
            "w = sys.argv[2]\n"
            "for i in range(120):\n"
            "    log.emit('rec', writer=w, i=i, pad='x' * 200)\n"
        )
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        procs = [
            subprocess.Popen([sys.executable, "-c", script, str(path),
                              name, root])
            for name in ("a", "b")
        ]
        for p in procs:
            assert p.wait(timeout=120) == 0
        raw = path.read_text().splitlines()
        assert len(raw) == 240
        recs = [json.loads(line) for line in raw]  # every line parses whole
        by_writer = {}
        for r in recs:
            by_writer.setdefault(r["writer"], []).append(r["i"])
        # Each writer's records arrive intact and in its own order.
        assert by_writer["a"] == list(range(120))
        assert by_writer["b"] == list(range(120))


# ------------------------------------------------------------- exporters ----
class TestExporters:
    def test_prometheus_text(self):
        reg = MetricsRegistry()
        reg.counter("fit/steps", 7)
        reg.gauge("engine/queue_depth", 3)
        reg.observe("span_seconds/decode", 0.002)
        text = export.prometheus_text(registry=reg)
        assert "# TYPE dtpu_fit_steps counter" in text
        assert "dtpu_fit_steps 7.0" in text
        assert "# TYPE dtpu_engine_queue_depth gauge" in text
        assert "# TYPE dtpu_span_seconds_decode histogram" in text
        assert 'dtpu_span_seconds_decode_bucket{le="+Inf"} 1' in text
        assert "dtpu_span_seconds_decode_count 1" in text

    def test_prometheus_histogram_cumulative(self):
        reg = MetricsRegistry()
        for v in (0.0005, 0.003, 0.2, 100.0):
            reg.observe("h", v)
        text = export.prometheus_text(registry=reg)
        # cumulative counts are nondecreasing and end at the total
        counts = [int(line.rsplit(" ", 1)[1])
                  for line in text.splitlines() if "_bucket{" in line]
        assert counts == sorted(counts) and counts[-1] == 4

    def test_write_prometheus_and_jsonl_snapshot(self, tmp_path):
        reg = MetricsRegistry()
        reg.counter("c", 1)
        p = export.write_prometheus(tmp_path / "metrics.prom", registry=reg)
        assert "dtpu_c 1.0" in p.read_text()
        j = export.append_snapshot(tmp_path / "snaps.jsonl", registry=reg,
                                   step=5)
        export.append_snapshot(j, registry=reg, step=6)
        recs = read_events(j)
        assert len(recs) == 2
        assert recs[0]["counters"]["c"] == 1.0 and recs[1]["step"] == 6


# ------------------------------------------------------------- aggregation --
def _snap(rank, seconds, world=2, step=5):
    return {"event": "metrics_snapshot", "ts": 0.0, "rank": rank,
            "world": world, "step": step, "self_seconds": list(seconds)}


class TestAggregate:
    def test_skew_report_and_straggler(self):
        events = [
            _snap(0, [0.01, 0.011, 0.009]),
            _snap(1, [0.05, 0.055, 0.06]),
            _snap(0, [0.01, 0.012]),
        ]
        rep = aggregate.skew_report(events)
        assert rep["world"] == 2 and rep["slowest_rank"] == 1
        assert rep["max_skew"] > 1.5
        row = aggregate.straggler(events, threshold=1.5)
        assert row["rank"] == 1 and row["skew"] == rep["max_skew"]

    def test_no_straggler_below_threshold(self):
        events = [_snap(0, [0.01] * 4), _snap(1, [0.011] * 4)]
        assert aggregate.straggler(events, threshold=1.5) is None
        assert aggregate.skew_report(events)["max_skew"] < 1.2

    def test_single_rank_never_straggles(self):
        events = [_snap(0, [0.01] * 4, world=1)]
        assert aggregate.straggler(events) is None

    def test_empty_stream(self):
        assert aggregate.skew_report([{"event": "attempt_start"}]) is None

    def test_falls_back_to_step_seconds(self):
        events = [
            {"event": "metrics_snapshot", "rank": 0,
             "step_seconds": [0.01]},
            {"event": "metrics_snapshot", "rank": 1,
             "step_seconds": [0.09]},
        ]
        assert aggregate.straggler(events, threshold=1.5)["rank"] == 1

    def test_supervisor_emits_straggler_event(self, tmp_path):
        """A scripted (no-subprocess) supervised run whose event log holds
        worker snapshot flushes: the terminal boundary must emit rank_skew
        + straggler events naming the slow rank."""
        log = EventLog(tmp_path / "ev.jsonl")
        for snap in (_snap(0, [0.01] * 5), _snap(1, [0.08] * 5)):
            log.emit(snap.pop("event"), **{k: v for k, v in snap.items()
                                           if k != "ts"})

        class OkLauncher:
            env_extra = {}

            def run(self, argv, num_workers, **kw):
                return [WorkerResult(index=i, ok=True)
                        for i in range(num_workers)]

        sup = Supervisor(["cmd"], 2, launcher=OkLauncher(), event_log=log,
                         straggler_threshold=1.5)
        result = sup.run(timeout=5.0)
        assert result.ok
        events = log.read()
        skews = [e for e in events if e["event"] == "rank_skew"]
        strag = [e for e in events if e["event"] == "straggler"]
        assert len(skews) == 1
        assert len(strag) == 1 and strag[0]["rank"] == 1

    def test_recovery_rows_reference_flight_dumps(self):
        events = [
            {"event": "attempt_start", "attempt": 1, "ts": 0.0},
            {"event": "fault_injected", "mode": "kill", "ts": 1.0},
            {"event": "flight_dump", "path": "/shm/flight-rank1.jsonl",
             "attempt": 1, "ts": 1.0},
            {"event": "attempt_end", "attempt": 1, "ok": False, "ts": 2.0},
            {"event": "attempt_start", "attempt": 2, "ts": 3.0},
            {"event": "attempt_end", "attempt": 2, "ok": True, "ts": 9.0},
        ]
        (row,) = recovery_rows(events)
        assert row["flight_dumps"] == ["/shm/flight-rank1.jsonl"]


# ---------------------------------------------------------------- faults ----
class TestSlowStepsFault:
    def test_slow_steps_persists_and_announces_once(self, monkeypatch):
        sleeps = []
        monkeypatch.setattr(time, "sleep", lambda s: sleeps.append(s))
        inj = FaultInjector("slow_steps", at_step=3, rank=0,
                            slow_seconds=0.2)
        for step in range(1, 7):
            inj.on_batch_end(None, step, {})
        assert sleeps == [0.2] * 4  # every step from at_step on
        assert inj.fired is False  # degradation, not a one-shot death
        assert inj._slow_announced is True

    def test_slow_steps_from_env(self, monkeypatch):
        monkeypatch.setenv(
            "DTPU_FAULT", "slow_steps:at_step=2,rank=1,slow_seconds=0.5"
        )
        inj = FaultInjector.from_env()
        assert inj.mode == "slow_steps" and inj.slow_seconds == 0.5
        assert inj.at_step == 2 and inj.rank == 1

    def test_kill_mode_dumps_flight(self, tmp_path, monkeypatch):
        monkeypatch.setenv("DTPU_FLIGHT_DIR", str(tmp_path))
        exits = []
        monkeypatch.setattr(os, "_exit", lambda code: exits.append(code))
        obs.default_recorder().record("step", step=4)
        inj = FaultInjector("kill", at_step=1, rank=0, exit_code=17)
        inj.on_batch_end(None, 1, {})
        assert exits == [17]
        dumps = list(tmp_path.glob("flight-rank*.jsonl"))
        assert dumps
        header = obs.flight.read_dump(dumps[0])[0]
        assert header["reason"] == "fault:kill"


# --------------------------------------------------- supervised gang e2e ----
_GANG_WORKER = """
import os, sys
sys.path.insert(0, os.environ["T_REPO"])
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import jax
jax.config.update("jax_platforms", "cpu")
import distributed_tpu as dtpu
from distributed_tpu.data.pipeline import Pipeline
from distributed_tpu.launch import report_result
from distributed_tpu.resilience import FaultInjector

spec = dtpu.cluster.initialize()
world = spec.num_processes
x, y = dtpu.data.synthetic_images(128, (8, 8), 10, 0)
strategy = dtpu.DataParallel() if world > 1 else dtpu.SingleDevice()
with strategy.scope():
    m = dtpu.Model(dtpu.nn.Sequential([
        dtpu.nn.Flatten(), dtpu.nn.Dense(32, activation="relu"),
        dtpu.nn.Dense(10),
    ]))
    m.compile(optimizer=dtpu.optim.SGD(0.05),
              loss="sparse_categorical_crossentropy")
m.build((8, 8))
cbs = list(filter(None, [FaultInjector.from_env()]))
with Pipeline(x, y, 32, seed=0, use_native=False,
              shard=(spec.index, world)) as p:
    m.fit(p, epochs=1, steps_per_epoch=6, verbose=0, callbacks=cbs)
report_result({"world": world, "final_step": int(m.step)})
"""


@pytest.mark.slow
def test_gang_kill_leaves_flight_dump_in_recovery_row(tmp_path):
    """Acceptance e2e: a FaultInjector kill on a REAL supervised 2-worker
    gang yields a readable flight-recorder dump, referenced from the
    recovery postmortem row (and renderable by dtpu-events)."""
    from distributed_tpu.resilience import RestartPolicy

    worker = tmp_path / "worker.py"
    worker.write_text(_GANG_WORKER)
    log = EventLog(tmp_path / "ev.jsonl")
    sup = Supervisor(
        [sys.executable, str(worker)], 2,
        policy=RestartPolicy(max_restarts=2, backoff=0.01, backoff_max=0.01),
        event_log=log,
        env_extra={
            "T_REPO": os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))),
            "DTPU_FAULT": "kill:at_step=3,rank=1",
            "DTPU_FAULT_MARKER": str(tmp_path / "once"),
        },
    )
    result = sup.run(timeout=300.0, grace=5.0)
    assert result.ok
    events = log.read()
    recov = [e for e in events if e["event"] == "recovery"]
    assert recov, "a kill-and-restart run must emit a recovery row"
    dumps = recov[0].get("flight_dumps")
    assert dumps, "the recovery row must reference the death's flight dump"
    records = obs.flight.read_dump(dumps[0])
    assert records and records[0]["kind"] == "flight_header"
    assert records[0]["reason"] == "fault:kill"
    steps = [r for r in records[1:] if r.get("kind") == "step"]
    assert steps, "the dump must hold the steps before death"
    # And the CLI renders it into the postmortem.
    out = cli.render(cli.summarize(events))
    assert "flight dump" in out
    assert "reason='fault:kill'" in out


# ------------------------------------------------------------ parity views --
# The PR 13 key sets (byte-compatible contract): these exact keys must
# still be present, and the legacy attributes must BE the registry's
# stored reports.
FIT_TELEMETRY_PR13_KEYS = {
    "input_wait", "dispatch", "checkpoint_wait", "total_seconds",
    "input_stall_fraction", "device_memory",
    "model_state_bytes_per_device", "precision", "comm_bytes_estimate",
}
RUN_TELEMETRY_PR13_KEYS = {
    "queue_wait", "prefill", "decode", "total_seconds",
    "input_stall_fraction", "kv_utilization", "generated_tokens",
    "tokens_per_sec", "time_to_first_token", "requests",
    "weights_version", "weight_swaps", "queue_depth", "free_blocks_min",
    "decode_steps", "prefill_dispatches", "preemptions",
}


class TestDerivedViewParity:
    def test_last_fit_telemetry_is_registry_view(self):
        x, y = dtpu.data.synthetic_images(64, (8, 8), 10, 0)
        m = small_model()
        m.fit(x, y, batch_size=16, epochs=1, steps_per_epoch=3, verbose=0)
        t = m.last_fit_telemetry
        assert FIT_TELEMETRY_PR13_KEYS <= set(t)
        assert t is obs.default_registry().get_report("model.fit")
        assert obs.default_registry().counter_value("fit/steps") > 0

    def test_last_run_telemetry_is_registry_view(self):
        m = dtpu.Model(dtpu.models.transformer_lm(
            32, num_layers=1, d_model=16, num_heads=2, max_len=32))
        m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
        m.build((8,))
        eng = dtpu.serving.Engine(m, max_slots=2, block_size=4, max_len=32)
        reqs = [(np.arange(1, 5, dtype=np.int32), 4),
                (np.arange(2, 8, dtype=np.int32), 4)]
        eng.run(reqs)
        t = eng.last_run_telemetry
        assert RUN_TELEMETRY_PR13_KEYS <= set(t)
        assert t is obs.default_registry().get_report("engine.run")
        reg = obs.default_registry()
        assert reg.gauge_value("engine/kv_utilization") is not None
        assert reg.counter_value("engine/requests") >= 2
        # span path: prefill/decode flowed through the tracer
        snap = reg.snapshot()
        assert "span_seconds/decode" in snap["histograms"]
        assert "span_seconds/prefill" in snap["histograms"]

    def test_fit_snapshot_flush_over_event_log(self, tmp_path, monkeypatch):
        monkeypatch.setenv("DTPU_EVENT_LOG", str(tmp_path / "ev.jsonl"))
        monkeypatch.setenv("DTPU_OBS_FLUSH_EVERY", "2")
        x, y = dtpu.data.synthetic_images(64, (8, 8), 10, 0)
        m = small_model()
        m.fit(x, y, batch_size=16, epochs=1, steps_per_epoch=4, verbose=0)
        snaps = aggregate.snapshots(read_events(tmp_path / "ev.jsonl"))
        assert snaps, "fit must flush metrics_snapshot over DTPU_EVENT_LOG"
        total = sum(len(s["step_seconds"]) for s in snaps)
        assert total == 4
        assert all(len(s["self_seconds"]) == len(s["step_seconds"])
                   for s in snaps)
        assert snaps[0]["rank"] == 0 and snaps[0]["world"] == 1


# -------------------------------------------------------------- logging ----
class TestLoggingRanks:
    def test_rank_world_defaults(self):
        r, w = rank_world()
        assert r == 0 and w >= 1

    def test_rank_world_from_env_spec(self, monkeypatch):
        """A jax-free controller resolves ranks from the cluster spec env
        (monkeypatching jax out of sys.modules to simulate)."""
        monkeypatch.setitem(sys.modules, "jax", None)
        monkeypatch.setenv("DTPU_CONFIG", json.dumps({
            "cluster": {"worker": ["a:1", "b:2", "c:3"]},
            "task": {"type": "worker", "index": 2},
        }))
        assert rank_world() == (2, 3)

    def test_jsonl_event_carries_rank_fields(self, tmp_path):
        from distributed_tpu.utils import logging as dlog
        dlog.set_jsonl(str(tmp_path / "log.jsonl"))
        try:
            dlog.event("step_rate", steps_per_sec=1.0)
        finally:
            dlog.set_jsonl(None)
        (rec,) = read_events(tmp_path / "log.jsonl")
        assert rec["process_index"] == 0 and rec["world_size"] >= 1

    def test_stderr_record_has_rankstamp(self):
        import logging as pylog
        logger = pylog.getLogger("distributed_tpu")
        record = logger.makeRecord("distributed_tpu", pylog.INFO, "f", 1,
                                   "msg", (), None)
        for f in logger.handlers[0].filters:
            f.filter(record)
        assert hasattr(record, "rankstamp")
        assert record.process_index == 0


# ------------------------------------------------------------------- CLI ----
class TestCli:
    def _write_log(self, tmp_path):
        log = EventLog(tmp_path / "ev.jsonl")
        log.emit("attempt_start", attempt=1, world_size=2)
        log.emit("fault_injected", mode="slow_steps", step=3)
        for snap in (_snap(0, [0.01] * 4), _snap(1, [0.08] * 4)):
            log.emit(snap.pop("event"),
                     **{k: v for k, v in snap.items() if k != "ts"})
        log.emit("attempt_end", attempt=1, ok=True, world_size=2)
        log.emit("run_complete", attempts=1)
        log.close()
        return tmp_path / "ev.jsonl"

    def test_summarize_and_render(self, tmp_path, capsys):
        path = self._write_log(tmp_path)
        rec = FlightRecorder()
        rec.record("step", step=7, seconds=0.01)
        dump = rec.dump(tmp_path / "flight.jsonl", reason="test")
        rc = cli.main([str(path), "--flight", str(dump)])
        assert rc == 0
        out = capsys.readouterr().out
        assert "postmortem" in out
        assert "attempt 1" in out
        assert "fault injected: slow_steps" in out
        assert "rank skew" in out
        assert "STRAGGLER: rank 1" in out
        assert "flight.jsonl" in out and "step=7" in out

    def test_json_mode(self, tmp_path, capsys):
        path = self._write_log(tmp_path)
        assert cli.main([str(path), "--json"]) == 0
        summary = json.loads(capsys.readouterr().out)
        assert summary["straggler"]["rank"] == 1
        assert summary["attempts"][0]["attempt"] == 1

    def test_missing_log(self, tmp_path, capsys):
        assert cli.main([str(tmp_path / "nope.jsonl")]) == 2

    def test_follow_tails_appends_and_rotation(self, tmp_path):
        """--follow yields events as they are appended, waits for a log
        that does not exist yet, and survives the writer's rotation
        (new inode) — the EventLog stat/reopen idiom from the reader
        side. Pull-based generator, so no threads needed to test it."""
        path = tmp_path / "ev.jsonl"
        gen = cli.follow(path, poll_s=0.01, stop=lambda: True)
        assert list(gen) == []  # no file yet + stop(): clean exit
        log = EventLog(path)
        log.emit("attempt_start", attempt=1, world_size=2)
        log.emit("stream_open", request_id=0, tenant="a")
        deadline = time.time() + 10  # hang guard, not the exit path
        seen = []
        gen = cli.follow(path, poll_s=0.01,
                         stop=lambda: time.time() > deadline)
        for e in gen:
            seen.append(e)
            if len(seen) == 2:
                break
        assert [e["event"] for e in seen] == ["attempt_start",
                                              "stream_open"]
        # Append while the generator is live: the next pull gets it.
        log.emit("quota_reject", tenant="flood")
        assert next(gen)["event"] == "quota_reject"
        # Rotate: unlink + fresh file. The tail reopens and keeps going.
        log.close()
        path.unlink()
        log2 = EventLog(path)
        log2.emit("run_complete", attempts=1)
        assert next(gen)["event"] == "run_complete"
        log2.close()
        gen.close()

    def test_follow_holds_back_torn_tail_line(self, tmp_path):
        path = tmp_path / "ev.jsonl"
        with open(path, "w") as f:
            f.write('{"event": "restart", "ts": 1.0, "attempt": 1}\n')
            f.write('{"event": "torn')  # no newline: write in progress
        gen = cli.follow(path, poll_s=0.01, stop=lambda: True)
        events = list(gen)
        assert [e["event"] for e in events] == ["restart"]
        # The tail completes -> the event is whole on the next tail.
        with open(path, "a") as f:
            f.write('_no_more", "ts": 2.0}\n')
        events = list(cli.follow(path, poll_s=0.01, stop=lambda: True))
        assert [e["event"] for e in events] == ["restart", "torn_no_more"]

    def test_event_line_rendering(self):
        line = cli.event_line({"ts": 0.0, "event": "replica_spawn",
                               "pid": 1, "replica": "decode-0",
                               "role": "decode"})
        assert line.endswith("replica_spawn replica=decode-0 role=decode")
