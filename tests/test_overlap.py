"""Overlapped host<->device execution (ISSUE 3): double-buffered input
prefetch + async checkpointing.

The acceptance bar: fit() with prefetch depth 2 and async checkpoints is
BIT-IDENTICAL to the synchronous path — including kill-restart-resume
through the supervisor — while the prefetch producer and checkpoint
writer threads never leak (conftest's autouse teardown asserts that after
every test here). bench.py's `overlap` mode measures the wall-clock win;
these tests pin the correctness half of the contract.
"""

import os
import signal
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

import jax

import distributed_tpu as dtpu
from distributed_tpu.checkpoint import core as ckpt_core
from distributed_tpu.data.prefetch import DevicePrefetcher
from distributed_tpu.resilience import PreemptionHandler
from distributed_tpu.training.callbacks import (
    LambdaCallback,
    ModelCheckpoint,
)
from distributed_tpu.utils.profiler import StepTimer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def small_data(n=256, seed=0):
    x, y = dtpu.data.synthetic_images(n, (28, 28), 10, seed)
    return x[..., None].astype(np.float32) / 255.0, y.astype(np.int32)


def make_model(K=None, momentum=0.9):
    m = dtpu.Model(dtpu.models.mnist_cnn())
    m.compile(
        optimizer=dtpu.optim.SGD(0.05, momentum=momentum),
        loss="sparse_categorical_crossentropy",
        metrics=["accuracy"],
        steps_per_execution=K,
    )
    return m


def assert_params_equal(a, b):
    for p, q in zip(jax.tree_util.tree_leaves(a.params),
                    jax.tree_util.tree_leaves(b.params)):
        np.testing.assert_array_equal(np.asarray(p), np.asarray(q))


# ------------------------------------------------------ DevicePrefetcher ----
class TestDevicePrefetcher:
    def test_serves_in_order_and_counts_steps(self):
        staged = []
        pf = DevicePrefetcher(lambda k: ("item", k), [2, 2, 1], depth=2)
        for want in (2, 2, 1):
            k, item = pf.get()
            assert k == want and item == ("item", want)
            staged.append(k)
        pf.close()
        assert pf.unconsumed_steps == 0

    def test_depth0_is_synchronous(self):
        calls = []
        pf = DevicePrefetcher(lambda k: calls.append(k), [1, 1, 1], depth=0)
        assert pf._thread is None  # no producer thread at depth 0
        pf.get()
        assert calls == [1]  # staged inline, exactly on demand
        pf.close()

    def test_early_close_reports_unconsumed_steps(self):
        # A slow consumer stops after one of four dispatches: the producer
        # staged ahead (depth 2) and those source steps must be reported
        # so a seekable source can rewind.
        produced = []

        def stage(k):
            produced.append(k)
            return k

        pf = DevicePrefetcher(stage, [3, 3, 3, 3], depth=2)
        k, _ = pf.get()
        time.sleep(0.3)  # let the producer fill the ring
        pf.close()
        assert k == 3
        assert pf.unconsumed_steps == sum(produced) - 3 > 0

    def test_producer_error_reraised_with_type(self):
        class Boom(RuntimeError):
            pass

        def stage(k):
            raise Boom("host prep failed")

        pf = DevicePrefetcher(stage, [1, 1], depth=2)
        with pytest.raises(Boom, match="host prep failed"):
            pf.get()
        pf.close()
        # depth 0: same contract, inline.
        pf0 = DevicePrefetcher(stage, [1, 1], depth=0)
        with pytest.raises(Boom):
            pf0.get()
        pf0.close()

    def test_close_is_idempotent_and_joins_thread(self):
        pf = DevicePrefetcher(lambda k: k, [1] * 8, depth=2)
        pf.get()
        pf.close()
        pf.close()
        assert not any(
            t.name == "dtpu-prefetch" and t.is_alive()
            for t in threading.enumerate()
        )


# ------------------------------------------------------- fit() overlap -----
class TestFitPrefetchParity:
    @pytest.mark.smoke
    def test_depth2_bitexact_vs_depth0_array_path(self):
        """ACCEPTANCE (parity half): prefetch depth 2 produces identical
        losses AND bit-identical final params to the synchronous path."""
        x, y = small_data()
        a, b = make_model(), make_model()
        ha = a.fit(x, y, batch_size=32, epochs=2, steps_per_epoch=6,
                   verbose=0, seed=0, prefetch=0)
        hb = b.fit(x, y, batch_size=32, epochs=2, steps_per_epoch=6,
                   verbose=0, seed=0, prefetch=2)
        assert ha.history["loss"] == hb.history["loss"]
        assert ha.history["accuracy"] == hb.history["accuracy"]
        assert_params_equal(a, b)

    # @slow (tier-1 budget, PR 17): ~5s prefetch x K x tail cross-
    # product; depth-2 bit-exactness and the tail schedule stay in-tier
    # in this class — this pins only the three-way composition.
    @pytest.mark.slow
    def test_depth2_bitexact_under_multi_step_with_tail(self):
        """Prefetch composes with steps_per_execution=K, including the
        tail dispatch smaller than K (steps_per_epoch=5, K=4 -> 4+1)."""
        x, y = small_data()
        a, b = make_model(4), make_model(4)
        a.fit(x, y, batch_size=32, epochs=2, steps_per_epoch=5, verbose=0,
              seed=0, prefetch=0)
        b.fit(x, y, batch_size=32, epochs=2, steps_per_epoch=5, verbose=0,
              seed=0, prefetch=2)
        assert a.step == b.step == 10
        assert_params_equal(a, b)

    def test_depth2_bitexact_pipeline_source(self):
        x, y = dtpu.data.synthetic_images(256, (28, 28), 10, seed=2)

        def run(depth):
            m = make_model(momentum=0.0)
            with dtpu.data.Pipeline(x[..., None], y, 32, seed=5,
                                    use_native=False) as p:
                m.fit(p, epochs=2, verbose=0, prefetch=depth)
            return m

        assert_params_equal(run(0), run(2))

    def test_prefetch_env_default_and_zero(self, monkeypatch):
        """fit(prefetch=None) reads DTPU_PREFETCH_DEPTH (default 2); the
        loop accepts 0 and negative values clamp to synchronous."""
        x, y = small_data(n=64)
        monkeypatch.setenv("DTPU_PREFETCH_DEPTH", "0")
        m = make_model()
        m.fit(x, y, batch_size=32, epochs=1, steps_per_epoch=2, verbose=0,
              seed=0)
        assert m.step == 2
        m2 = make_model()
        m2.fit(x, y, batch_size=32, epochs=1, steps_per_epoch=2, verbose=0,
               seed=0, prefetch=-3)
        assert_params_equal(m, m2)

    def test_stop_training_rewinds_seekable_source(self):
        """A mid-epoch stop leaves the prefetcher holding staged batches;
        a seekable source is rewound so its cursor equals the steps the
        model actually trained — resume alignment preserved."""
        x, y = dtpu.data.synthetic_images(256, (28, 28), 10, seed=3)
        p = dtpu.data.Pipeline(x[..., None], y, 32, seed=1,
                               use_native=False)
        m = make_model()
        stop = LambdaCallback(
            on_batch_end=lambda mm, s, logs: setattr(
                mm, "stop_training", s >= 3)
        )
        m.fit(p, epochs=2, verbose=0, callbacks=[stop], prefetch=2)
        assert m.step == 3
        assert p.steps_emitted == 3  # rewound past the staged lookahead
        p.close()

    def test_telemetry_attributes_stall_buckets(self):
        x, y = small_data(n=128)
        m = make_model()
        m.fit(x, y, batch_size=32, epochs=1, steps_per_epoch=4, verbose=0,
              seed=0, prefetch=2)
        t = m.last_fit_telemetry
        assert set(t) >= {"input_wait", "dispatch", "checkpoint_wait",
                          "total_seconds", "input_stall_fraction"}
        assert t["dispatch"] > 0  # donated dispatches wait on the device
        assert 0.0 <= t["input_stall_fraction"] <= 1.0
        assert t["total_seconds"] >= t["input_wait"]
        assert m._stall_timer is None  # detached at fit end


# -------------------------------------------------- async checkpointing ----
class TestAsyncCheckpointer:
    def test_async_save_lands_after_wait_and_restores(self, tmp_path):
        x, y = small_data(n=128)
        m = make_model()
        m.fit(x, y, batch_size=32, epochs=1, steps_per_epoch=3, verbose=0,
              seed=0, prefetch=0)
        ck = dtpu.Checkpointer(tmp_path, async_save=True)
        ck.save(m)
        ck.wait()
        assert ck.all_steps() == [3]
        assert ck.latest_step() == 3
        restored = make_model()
        assert ck.restore_into(restored) == 3
        assert_params_equal(m, restored)

    def test_async_snapshot_is_donation_safe(self, tmp_path):
        """The step that runs AFTER save() donates the params buffers the
        snapshot copied — the written checkpoint must hold the values at
        save time, not the post-donation ones."""
        x, y = small_data(n=128)
        m = make_model()
        m.fit(x, y, batch_size=32, epochs=1, steps_per_epoch=3, verbose=0,
              seed=0, prefetch=0)
        want = [np.asarray(l).copy()
                for l in jax.tree_util.tree_leaves(m.params)]
        ck = dtpu.Checkpointer(tmp_path, async_save=True)
        ck.save(m)  # returns before the write; snapshot taken on device
        m.fit(x, y, batch_size=32, epochs=1, steps_per_epoch=3, verbose=0,
              seed=0, prefetch=0)  # donates the original buffers
        ck.wait()
        tree, meta = ckpt_core.load_npz(tmp_path / "ckpt-3.npz")
        got = jax.tree_util.tree_leaves(tree["params"])
        for w, g in zip(want, got):
            np.testing.assert_array_equal(w, g)

    def test_newer_save_waits_out_older_write(self, tmp_path):
        """Same step family: save(step=N+1) must not race the in-flight
        write of step N for the latest pointer."""
        x, y = small_data(n=128)
        m = make_model()
        m.fit(x, y, batch_size=32, epochs=1, steps_per_epoch=2, verbose=0,
              seed=0, prefetch=0)
        ck = dtpu.Checkpointer(tmp_path, keep=5, async_save=True)
        ck.save(m, step=2)
        ck.save(m, step=4)  # waits out the step-2 writer first
        ck.wait()
        assert ck.all_steps() == [2, 4]
        assert ck._read_latest_pointer() == 4

    def test_writer_error_surfaces_at_wait(self, tmp_path, monkeypatch):
        x, y = small_data(n=128)
        m = make_model()
        m.fit(x, y, batch_size=32, epochs=1, steps_per_epoch=2, verbose=0,
              seed=0, prefetch=0)
        ck = dtpu.Checkpointer(tmp_path, async_save=True)

        def boom(*a, **kw):
            raise OSError("disk full")

        monkeypatch.setattr(ckpt_core, "save_npz", boom)
        ck.save(m)
        with pytest.raises(OSError, match="disk full"):
            ck.wait()
        ck.wait()  # error is consumed, not re-raised forever

    def test_corrupt_latest_fallback_still_works(self, tmp_path):
        """PR 2's corrupt-latest fallback composes with the async writer:
        auto-restore skips a clobbered newest file and falls back."""
        x, y = small_data(n=128)
        ck = dtpu.Checkpointer(tmp_path, async_save=True)
        m = make_model()
        for steps in (2, 2):
            m.fit(x, y, batch_size=32, epochs=1, steps_per_epoch=steps,
                  verbose=0, seed=0, prefetch=0)
            ck.save(m)
        ck.wait()
        assert ck.all_steps() == [2, 4]
        (tmp_path / "ckpt-4.npz").write_bytes(b"torn garbage")
        restored = make_model()
        assert ck.restore_into(restored) == 2

    def test_fit_parity_async_ckpt_plus_prefetch(self, tmp_path):
        """ACCEPTANCE: fit with prefetch depth 2 + async ModelCheckpoint
        matches the fully synchronous run bit-exactly, and the directory
        is complete (flushed) the moment fit returns."""
        x, y = small_data()
        a = make_model()
        a.fit(x, y, batch_size=32, epochs=2, steps_per_epoch=4, verbose=0,
              seed=1, prefetch=0,
              callbacks=[ModelCheckpoint(tmp_path / "sync",
                                         save_freq="epoch")])
        b = make_model()
        b.fit(x, y, batch_size=32, epochs=2, steps_per_epoch=4, verbose=0,
              seed=1, prefetch=2,
              callbacks=[ModelCheckpoint(tmp_path / "async",
                                         save_freq="epoch",
                                         async_save=True)])
        assert_params_equal(a, b)
        # Writer flushed at train end: both dirs hold the same steps NOW.
        assert (dtpu.Checkpointer(tmp_path / "sync").all_steps()
                == dtpu.Checkpointer(tmp_path / "async").all_steps()
                == [4, 8])
        ra = make_model()
        dtpu.Checkpointer(tmp_path / "async").restore_into(ra)
        assert_params_equal(a, ra)

    def test_sharded_async_is_supported_and_buddy_needs_sharded(
            self, tmp_path):
        """The old sharded+async restriction is LIFTED (ISSUE 13: the
        shard write backgrounds, the cross-host commit defers to the next
        main-thread wait — tests/test_sharded_checkpoint.py pins the
        mechanics); the buddy tier still requires the sharded format."""
        ModelCheckpoint(tmp_path, sharded=True, async_save=True)  # no raise
        dtpu.checkpoint.ShardedCheckpointer(tmp_path).wait()  # no-op
        with pytest.raises(ValueError, match="sharded=True"):
            ModelCheckpoint(tmp_path, buddy=tmp_path / "store")


# ------------------------------------------------------- preemption flush ---
class TestPreemptionFlush:
    def test_preemption_flushes_async_writers_before_marker(self, tmp_path):
        """SIGTERM with an async ModelCheckpoint live: every background
        write lands, THEN the final checkpoint saves synchronously — the
        newest step on disk is the preemption step, complete and
        loadable, before fit returns (in-process mode stands in for the
        exit-75 path, same flush ordering)."""
        x, y = small_data()
        send = LambdaCallback(
            on_batch_end=lambda m, s, logs: (
                os.kill(os.getpid(), signal.SIGTERM) if s == 5 else None
            )
        )
        handler = PreemptionHandler(tmp_path, exit_code=None)
        m = make_model()
        m.fit(x, y, batch_size=32, epochs=2, steps_per_epoch=4, verbose=0,
              seed=7, prefetch=2,
              callbacks=[ModelCheckpoint(tmp_path, save_freq=2,
                                         async_save=True), send, handler])
        assert handler.triggered and m.step == 5
        ck = dtpu.Checkpointer(tmp_path)
        assert ck.latest_step() == 5
        assert ck.is_valid(5)  # complete npz, not a torn async tail
        restored = make_model()
        assert ck.restore_into(restored) == 5

    def test_wait_all_async_is_global_barrier(self, tmp_path):
        x, y = small_data(n=128)
        m = make_model()
        m.fit(x, y, batch_size=32, epochs=1, steps_per_epoch=2, verbose=0,
              seed=0, prefetch=0)
        cks = [dtpu.Checkpointer(tmp_path / f"d{i}", async_save=True)
               for i in range(3)]
        for ck in cks:
            ck.save(m)
        ckpt_core.wait_all_async()
        for ck in cks:
            assert ck.all_steps() == [2]
        assert not any(
            t.name == "dtpu-ckpt-writer" and t.is_alive()
            for t in threading.enumerate()
        )


# ----------------------------------------------- supervisor end to end -----
OVERLAP_WORKER = """
    import os, sys
    sys.path.insert(0, {repo!r})
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import distributed_tpu as dtpu
    from distributed_tpu.launch import report_result
    from distributed_tpu.resilience import FaultInjector
    from distributed_tpu.training.callbacks import ModelCheckpoint

    CKPT = os.environ["TEST_CKPT_DIR"]
    x, y = dtpu.data.synthetic_images(256, (28, 28), 10, 0)
    x = x[..., None].astype(np.float32) / 255.0
    m = dtpu.Model(dtpu.models.mnist_cnn())
    m.compile(optimizer=dtpu.optim.SGD(0.05), metrics=["accuracy"])
    cbs = [ModelCheckpoint(CKPT, save_freq=3, restore=True,
                           async_save=os.environ.get("TEST_ASYNC") == "1")]
    fault = FaultInjector.from_env()
    if fault is not None:
        cbs.append(fault)
    hist = m.fit(x, y.astype(np.int32), batch_size=64, epochs=2,
                 steps_per_epoch=4, verbose=0, seed=0, callbacks=cbs,
                 prefetch=int(os.environ.get("TEST_PREFETCH", "2")))
    leaf = np.asarray(jax.tree_util.tree_leaves(m.params)[0]).ravel()[:4]
    report_result({{"loss": hist.metrics["loss"][-1],
                   "leaf": [float(v) for v in leaf]}})
    """


# @slow (tier-1 budget, PR 16): ~11s subprocess e2e; the supervised
# kill-restart-resume path stays in tier-1 via test_resilience.py's
# parity acceptance, and prefetch/async resume correctness is covered
# by the in-process resume tests above.
@pytest.mark.slow
def test_supervisor_kill_restart_resume_with_overlap(tmp_path):
    """ACCEPTANCE (end to end): a supervised worker running fit with
    prefetch depth 2 + async checkpoints is fault-killed mid-run; the
    supervisor restarts it, the checkpoint resumes, and the final params
    match a fully synchronous uninterrupted run bit-for-bit."""
    from distributed_tpu.launch import LocalLauncher
    from distributed_tpu.resilience import RestartPolicy, Supervisor
    from distributed_tpu.utils.events import EventLog

    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(OVERLAP_WORKER.format(repo=REPO)))

    # Reference: synchronous (prefetch 0, sync saves), uninterrupted.
    ref = LocalLauncher(env_extra={
        "TEST_CKPT_DIR": str(tmp_path / "ck_ref"),
        "TEST_PREFETCH": "0",
        "TEST_ASYNC": "0",
    }).run([sys.executable, str(script)], 1, timeout=300)
    assert ref[0].ok, (ref[0].error, ref[0].log_tail[-600:])

    log = EventLog(tmp_path / "events.jsonl")
    sup = Supervisor(
        [sys.executable, str(script)], 1,
        policy=RestartPolicy(max_restarts=2, backoff=0.05, backoff_max=0.1),
        checkpoint_dir=tmp_path / "ck",
        event_log=log,
        env_extra={
            "TEST_CKPT_DIR": str(tmp_path / "ck"),
            "TEST_PREFETCH": "2",
            "TEST_ASYNC": "1",
            "DTPU_FAULT": "kill:at_step=5",  # mid-epoch-2 (4 steps/epoch)
            "DTPU_FAULT_MARKER": str(tmp_path / "fault_once"),
        },
    )
    out = sup.run(timeout=300, grace=5)
    assert out.ok, [(r.index, r.error, r.log_tail[-600:])
                    for r in out.results]
    assert out.attempts == 2 and out.restarts_used == 1
    value = out.results[0].value
    assert value["loss"] == pytest.approx(ref[0].value["loss"], rel=1e-6)
    np.testing.assert_allclose(value["leaf"], ref[0].value["leaf"],
                               rtol=1e-6)
    restart = next(e for e in log.read() if e["event"] == "restart")
    # The async save at step 3 was fully flushed before the kill at 5.
    assert restart["resume_step"] == 3
