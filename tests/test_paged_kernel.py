"""Fused paged-attention decode kernel (ops.paged_attention).

Two layers of parity pin the kernel:

- kernel-level matrix: the fused gather+attention output vs a dense
  gather-then-softmax reference over block_size x head_dim x dtype x
  candidate-width (the paged_verify K), including int8 {"q","scale"}
  pools dequantized in-kernel;
- engine-level token-exactness: ``Engine(decode_kernel="fused")`` must
  serve exactly the tokens the reference path serves across batch churn,
  preemption pressure, prefix-cache admission, int8 KV and speculative
  verify — plus the no-recompile contract across batch churn.

Also pins the ``_paged_view`` int8 mask-before-dequantize fix: rows the
causal mask can never expose dequantize to exact zeros, never
``garbage * scale``.

Kept lean for the 1-core tier-1 box: the kernel runs in Pallas interpret
mode here; heavy matrix cells are @slow.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import assert_no_recompile

import distributed_tpu as dtpu
from distributed_tpu.ops import paged_attention as paged_ops
from distributed_tpu.quant import QKEY, SKEY, dequantize
from distributed_tpu.serving import Engine, Request


# ------------------------------------------------------- kernel-level matrix --
def _dense_ref(q, k_pool, v_pool, tables, positions):
    """Gather-then-dense reference: what the fused kernel must reproduce."""
    s, kw, h, hd = q.shape
    if isinstance(k_pool, dict):
        k_pool = dequantize(k_pool, q.dtype)
        v_pool = dequantize(v_pool, q.dtype)
    gk = np.asarray(k_pool)[tables]  # (s, nb, bs, h, hd)
    gv = np.asarray(v_pool)[tables]
    nb, bs = gk.shape[1], gk.shape[2]
    ll = nb * bs
    k = gk.reshape(s, ll, h, hd).astype(np.float32)
    v = gv.reshape(s, ll, h, hd).astype(np.float32)
    q32 = np.asarray(q).astype(np.float32)
    col = np.arange(ll)[None, None, :]
    row = (np.asarray(positions)[:, None] + np.arange(kw)[None, :])[..., None]
    vis = col <= row  # (s, kw, ll)
    sc = np.einsum("skhd,slhd->skhl", q32, k) / math.sqrt(hd)
    sc = np.where(vis[:, :, None, :], sc, -1e30)
    p = np.exp(sc - sc.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return np.einsum("skhl,slhd->skhd", p, v)


def _quantize_pool(pool):
    """Row-wise per-(position, head) int8 pair, the KV-scatter scheme."""
    amax = np.max(np.abs(pool), axis=-1, keepdims=True)
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.round(pool / scale), -127, 127).astype(np.int8)
    return {QKEY: jnp.asarray(q), SKEY: jnp.asarray(scale)}


def _case(seed, s, nb, bs, h, hd, kw, dtype, int8=False):
    rng = np.random.default_rng(seed)
    nblocks = s * nb + 1
    kp = rng.standard_normal((nblocks, bs, h, hd)).astype(np.float32)
    vp = rng.standard_normal((nblocks, bs, h, hd)).astype(np.float32)
    q = rng.standard_normal((s, kw, h, hd)).astype(np.float32)
    # Every slot owns a disjoint table; positions spread across the span
    # (early rows leave whole blocks invisible — the masked-gather case).
    tables = (1 + np.arange(s * nb).reshape(s, nb)).astype(np.int32)
    positions = rng.integers(0, nb * bs - kw + 1, (s,)).astype(np.int32)
    if int8:
        k_pool, v_pool = _quantize_pool(kp), _quantize_pool(vp)
    else:
        k_pool = jnp.asarray(kp, dtype)
        v_pool = jnp.asarray(vp, dtype)
    return jnp.asarray(q, dtype), k_pool, v_pool, tables, positions


MATRIX = [
    # (block_size, head_dim, dtype, kw, int8, slow)
    (4, 4, jnp.float32, 1, False, False),
    (4, 8, jnp.float32, 3, False, False),
    (4, 4, jnp.bfloat16, 1, False, False),
    (4, 4, jnp.float32, 1, True, False),
    (4, 8, jnp.float32, 3, True, False),
    (8, 16, jnp.float32, 2, False, True),
    (16, 8, jnp.bfloat16, 3, False, True),
    (16, 4, jnp.bfloat16, 2, True, True),
]


@pytest.mark.parametrize(
    "bs,hd,dtype,kw,int8",
    [pytest.param(bs, hd, dt, kw, q8,
                  marks=[pytest.mark.slow] if slow else [],
                  id=f"bs{bs}-hd{hd}-{jnp.dtype(dt).name}-kw{kw}"
                     f"{'-int8' if q8 else ''}")
     for bs, hd, dt, kw, q8, slow in MATRIX],
)
def test_fused_kernel_matches_dense_reference(bs, hd, dtype, kw, int8):
    q, k_pool, v_pool, tables, positions = _case(
        seed=bs * 100 + hd + kw, s=3, nb=3, bs=bs, h=2, hd=hd, kw=kw,
        dtype=dtype, int8=int8)
    got = np.asarray(paged_ops.paged_attention(
        q, k_pool, v_pool, jnp.asarray(tables), jnp.asarray(positions)
    )).astype(np.float32)
    want = _dense_ref(q, k_pool, v_pool, tables, positions)
    tol = 2e-2 if dtype == jnp.bfloat16 else 2e-5
    np.testing.assert_allclose(got, want, rtol=tol, atol=tol)


def test_kernel_ignores_trash_and_future_rows():
    """Poison every row the causal mask hides (the trash block and the
    positions past each slot's write head) with huge values: the output
    must not move. This is the failure mode the fused mask exists for —
    inactive table slots all point at block 0."""
    q, k_pool, v_pool, tables, positions = _case(
        seed=7, s=2, nb=2, bs=4, h=2, hd=4, kw=1, dtype=jnp.float32)
    clean = np.asarray(paged_ops.paged_attention(
        q, k_pool, v_pool, jnp.asarray(tables), jnp.asarray(positions)))
    kp = np.asarray(k_pool).copy()
    vp = np.asarray(v_pool).copy()
    kp[0] = 1e30  # trash block
    vp[0] = 1e30
    ll = tables.shape[1] * 4
    for s, pos in enumerate(positions):
        for j in range(int(pos) + 1, ll):  # rows past the write head
            kp[tables[s, j // 4], j % 4] = 1e30
            vp[tables[s, j // 4], j % 4] = 1e30
    poisoned = np.asarray(paged_ops.paged_attention(
        q, jnp.asarray(kp), jnp.asarray(vp), jnp.asarray(tables),
        jnp.asarray(positions)))
    np.testing.assert_array_equal(clean, poisoned)


def test_decode_kernel_scope_is_threadlocal_and_validated():
    assert paged_ops.current_decode_kernel() == paged_ops.REFERENCE
    with paged_ops.decode_kernel_scope(paged_ops.FUSED):
        assert paged_ops.current_decode_kernel() == paged_ops.FUSED
    assert paged_ops.current_decode_kernel() == paged_ops.REFERENCE
    with pytest.raises(ValueError, match="decode_kernel"):
        with paged_ops.decode_kernel_scope("bogus"):
            pass


# ------------------------------------------------- _paged_view int8 masking --
def test_paged_view_int8_masks_before_dequantize():
    """Invisible rows must dequantize to exact zeros (payload -> 0,
    scale -> 1) BEFORE the multiply: ``garbage * scale`` from the trash
    block or stale rows — including non-finite scales — must never reach
    the attention program."""
    mha = dtpu.nn.MultiHeadAttention(2)
    rng = np.random.default_rng(3)
    s, nb, bs, h, hd = 2, 2, 4, 2, 4
    pool = rng.standard_normal((s * nb + 1, bs, h, hd)).astype(np.float32)
    qpool = _quantize_pool(pool)
    tables = jnp.asarray(
        (1 + np.arange(s * nb).reshape(s, nb)).astype(np.int32))
    ll = nb * bs
    visible = jnp.asarray(
        np.arange(ll)[None, :] <= np.array([[2], [5]]))  # (s, ll)
    clean = np.asarray(
        mha._paged_view(qpool, tables, jnp.float32, visible=visible))
    # Poison the hidden rows with inf scales and max payloads.
    qq = np.asarray(qpool[QKEY]).copy()
    ss = np.asarray(qpool[SKEY]).copy()
    vis = np.asarray(visible)
    for si in range(s):
        for j in range(ll):
            if not vis[si, j]:
                qq[tables[si, j // bs], j % bs] = 127
                ss[tables[si, j // bs], j % bs] = np.inf
    poisoned = np.asarray(mha._paged_view(
        {QKEY: jnp.asarray(qq), SKEY: jnp.asarray(ss)}, tables,
        jnp.float32, visible=visible))
    assert np.all(np.isfinite(poisoned))
    np.testing.assert_array_equal(clean, poisoned)
    # And the hidden rows are exact zeros, bit-matching the fused kernel's
    # never-weighted treatment.
    assert np.array_equal(poisoned[~vis], np.zeros_like(poisoned[~vis]))


# --------------------------------------------------- engine token-exactness --
@pytest.fixture(scope="module")
def lm():
    model = dtpu.Model(dtpu.models.transformer_lm(
        32, num_layers=2, d_model=16, num_heads=2, max_len=64))
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    model.build((16,))
    return model


def _requests(seed=0, n=5, vocab=32, p_range=(1, 9), m_range=(3, 9)):
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(0, vocab, (int(t),)).astype(np.int32)
               for t in rng.integers(*p_range, n)]
    news = [int(m) for m in rng.integers(*m_range, n)]
    return prompts, news


def _run_both(lm, prompts, news, **kwargs):
    outs = {}
    for kind in (paged_ops.REFERENCE, paged_ops.FUSED):
        engine = Engine(lm, max_slots=2, block_size=4, max_len=64,
                        decode_kernel=kind, **kwargs)
        outs[kind] = engine.run(
            [Request(p, m) for p, m in zip(prompts, news)])
    return outs


def _assert_token_exact(outs):
    for i, (w, g) in enumerate(zip(outs[paged_ops.REFERENCE],
                                   outs[paged_ops.FUSED])):
        assert np.array_equal(w, g), (
            f"request {i}: fused {list(g)} != reference {list(w)}")


def test_engine_fused_greedy_parity_with_churn(lm):
    """More requests than slots: admits mid-decode churn the batch
    composition while the fused kernel serves every dispatch."""
    prompts, news = _requests(seed=0, n=5)
    _assert_token_exact(_run_both(lm, prompts, news))


@pytest.mark.slow
def test_engine_fused_int8_kv_parity(lm):
    """In-tier coverage of int8 dequant lives in the kernel matrix cells
    and test_paged_view_int8_masks_before_dequantize; the end-to-end
    engine run is a whale (its own int8 decode compile)."""
    prompts, news = _requests(seed=1, n=4)
    _assert_token_exact(_run_both(lm, prompts, news, kv_dtype="int8"))


def test_engine_fused_preemption_parity(lm):
    """Pool too small for the working set: victims are evicted and
    re-prefilled; the fused path must survive the re-admission. The
    pool (5 blocks = 4 usable at block_size 4) cannot back two contexts
    that grow past 13 tokens combined, so a running slot's mid-decode
    ``reserve`` fails and evicts the youngest — asserted via telemetry
    so the config can't silently stop exercising the path."""
    prompts, news = _requests(seed=2, n=4, m_range=(6, 10))
    outs = {}
    for kind in (paged_ops.REFERENCE, paged_ops.FUSED):
        engine = Engine(lm, max_slots=2, block_size=4, max_len=64,
                        num_blocks=5, decode_kernel=kind)
        outs[kind] = engine.run(
            [Request(p, m) for p, m in zip(prompts, news)])
        assert engine.last_run_telemetry["preemptions"] > 0, (
            f"{kind}: pool never hit pressure — preemption not exercised")
    _assert_token_exact(outs)


@pytest.mark.slow
def test_engine_fused_prefix_cache_parity(lm):
    """Shared leading span: prefix-store admission hands the fused path
    refcounted blocks it never prefilled itself. @slow: the admission
    path is scheduler-side (kernel-independent); churn + preemption keep
    the in-tier engine coverage."""
    rng = np.random.default_rng(4)
    common = rng.integers(0, 32, (8,)).astype(np.int32)
    prompts = [np.concatenate([common,
                               rng.integers(0, 32, (int(t),)).astype(np.int32)])
               for t in rng.integers(1, 5, 4)]
    news = [5, 6, 4, 7]
    _assert_token_exact(_run_both(lm, prompts, news, prefix_cache=True))


@pytest.mark.slow
def test_engine_fused_spec_verify_parity(lm):
    """Speculative decoding: the K-candidate verify dispatch goes through
    the fused kernel's kw > 1 path."""
    draft = dtpu.Model(dtpu.models.transformer_lm(
        32, num_layers=1, d_model=8, num_heads=2, max_len=64))
    draft.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    draft.build((16,))
    prompts, news = _requests(seed=5, n=4)
    _assert_token_exact(
        _run_both(lm, prompts, news, draft_model=draft, spec_k=3))


def test_engine_fused_no_recompile_on_batch_churn(lm):
    """The fused decode/verify dispatches jit once: a second run with a
    different request mix must reuse the compiled programs."""
    engine = Engine(lm, max_slots=2, block_size=4, max_len=64,
                    decode_kernel="fused")
    prompts, news = _requests(seed=6, n=4)
    engine.run([Request(p, m) for p, m in zip(prompts, news)])
    prompts2, news2 = _requests(seed=7, n=5, p_range=(2, 12))
    with assert_no_recompile(engine._decode_jit):
        engine.run([Request(p, m) for p, m in zip(prompts2, news2)])


def test_engine_validates_decode_kernel(lm):
    with pytest.raises(ValueError, match="decode_kernel"):
        Engine(lm, max_slots=2, block_size=4, decode_kernel="bogus")


def test_engine_programs_selects_kernel(lm):
    from distributed_tpu.fleet.replica import EnginePrograms
    progs = EnginePrograms(lm, decode_kernel="fused")
    assert progs.decode_kernel == "fused"
    with pytest.raises(ValueError, match="decode_kernel"):
        EnginePrograms(lm, decode_kernel="bogus")
