"""Pallas fused softmax cross-entropy: parity with the stock loss in value
and gradient (interpret mode on CPU; Mosaic on real TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import distributed_tpu as dtpu
from distributed_tpu.ops import losses
from distributed_tpu.ops.pallas_kernels import (
    fused_softmax_xent,
    pallas_sparse_categorical_crossentropy,
)


def _case(n, c, seed=0, dtype=jnp.float32):
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    logits = (jax.random.normal(k1, (n, c)) * 3.0).astype(dtype)
    labels = jax.random.randint(k2, (n,), 0, c)
    return logits, labels


@pytest.mark.parametrize("n,c", [(8, 10), (37, 10), (64, 1000), (5, 130)])
def test_forward_matches_reference(n, c):
    logits, labels = _case(n, c)
    got = fused_softmax_xent(logits, labels)
    ref = -jax.nn.log_softmax(logits, axis=-1)[jnp.arange(n), labels]
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("n,c", [(16, 10), (37, 257)])
def test_gradient_matches_reference(n, c):
    logits, labels = _case(n, c, seed=1)

    def fused(lg):
        return jnp.mean(fused_softmax_xent(lg, labels))

    def ref(lg):
        return losses.sparse_categorical_crossentropy(lg, labels)

    gf = jax.grad(fused)(logits)
    gr = jax.grad(ref)(logits)
    np.testing.assert_allclose(gf, gr, rtol=1e-5, atol=1e-6)


def test_bf16_logits():
    logits, labels = _case(24, 50, seed=2, dtype=jnp.bfloat16)
    got = fused_softmax_xent(logits, labels)
    ref = -jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)[
        jnp.arange(24), labels
    ]
    np.testing.assert_allclose(got, ref, rtol=1e-2, atol=1e-2)
    g = jax.grad(lambda lg: jnp.mean(fused_softmax_xent(lg, labels)))(logits)
    assert g.dtype == jnp.bfloat16


def test_token_level_shape():
    # (B, T, C) flattening path of the registry-level loss.
    logits = jax.random.normal(jax.random.PRNGKey(3), (4, 7, 11))
    labels = jax.random.randint(jax.random.PRNGKey(4), (4, 7), 0, 11)
    got = pallas_sparse_categorical_crossentropy(logits, labels)
    ref = losses.sparse_categorical_crossentropy(logits, labels)
    np.testing.assert_allclose(got, ref, rtol=1e-5)


def test_jit_and_registry():
    loss_fn = losses.get("pallas_sparse_categorical_crossentropy")
    logits, labels = _case(32, 10, seed=5)
    jitted = jax.jit(loss_fn)
    np.testing.assert_allclose(
        jitted(logits, labels),
        losses.sparse_categorical_crossentropy(logits, labels),
        rtol=1e-5,
    )
    per_ex = losses.get_per_example(loss_fn)
    assert per_ex is not None
    assert per_ex(logits, labels).shape == (32,)


def test_large_class_count_falls_back():
    from distributed_tpu.ops import pallas_kernels as pk

    n, c = 4, pk.MAX_FUSED_CLASSES + 128
    logits = jax.random.normal(jax.random.PRNGKey(7), (n, c))
    labels = jnp.array([0, 1, 2, 3])
    # Registry-level loss silently falls back to the stock implementation...
    got = pallas_sparse_categorical_crossentropy(logits, labels)
    ref = losses.sparse_categorical_crossentropy(logits, labels)
    np.testing.assert_allclose(got, ref, rtol=1e-5)
    per_ex = pk.per_example_pallas_xent(logits, labels)
    assert per_ex.shape == (n,)
    # ...while the raw kernel refuses loudly.
    with pytest.raises(ValueError, match="classes"):
        fused_softmax_xent(logits, labels)


def test_trains_mnist_cnn():
    # End-to-end: compile with the fused loss; training must still learn.
    model = dtpu.Model(dtpu.models.mnist_cnn())
    model.compile(
        optimizer=dtpu.optim.SGD(0.1),
        loss="pallas_sparse_categorical_crossentropy",
        metrics=["accuracy"],
    )
    x, y = dtpu.data.synthetic_images(256, (28, 28), 10, seed=6)
    x = x[..., None].astype(np.float32) / 255.0
    hist = model.fit(x, y, batch_size=64, epochs=3, verbose=0)
    assert hist.history["loss"][-1] < hist.history["loss"][0]
    ev = model.evaluate(x[:100], y[:100], batch_size=64, verbose=0)
    assert np.isfinite(ev["loss"])
