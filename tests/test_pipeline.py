"""Native C++ input pipeline + Python fallback + fit() iterator mode.

The native library is the framework's host-side native component (SURVEY.md
§2b: the reference's hot path runs in TF's C++ core; here host batch prep
runs in C++ worker threads). g++ is present in CI, so the native path is
exercised for real, and the fallback is forced via use_native=False.
"""

import numpy as np
import pytest

import distributed_tpu as dtpu
from distributed_tpu.data import Pipeline, native_available


def _dataset(n=64, shape=(8, 8, 1), classes=10, seed=0):
    return dtpu.data.synthetic_images(n, shape, classes, seed)


NATIVE_PARAMS = [
    pytest.param(True, marks=pytest.mark.skipif(
        not native_available(), reason="no C++ toolchain")),
    False,
]


@pytest.mark.parametrize("use_native", NATIVE_PARAMS)
class TestPipeline:
    @pytest.mark.smoke
    def test_shapes_dtypes_normalization(self, use_native):
        x, y = _dataset()
        p = Pipeline(x, y, 16, shuffle=False, use_native=use_native)
        xb, yb = next(p)
        assert xb.shape == (16, 8, 8, 1) and xb.dtype == np.float32
        assert yb.shape == (16,) and yb.dtype == np.int32
        # shuffle=False: first batch is rows 0..15 normalized
        np.testing.assert_allclose(xb, x[:16].astype(np.float32) / 255.0)
        np.testing.assert_array_equal(yb, y[:16])
        p.close()

    def test_each_pass_covers_all_rows(self, use_native):
        x, y = _dataset(n=48)
        y = np.arange(48, dtype=np.int32)  # labels identify rows
        p = Pipeline(x, y, 12, shuffle=True, seed=3, use_native=use_native)
        assert p.steps_per_pass == 4
        for _pass in range(2):
            seen = []
            for _ in range(4):
                _, yb = next(p)
                seen.extend(yb.tolist())
            assert sorted(seen) == list(range(48))
        p.close()

    def test_deterministic_across_instances(self, use_native):
        x, y = _dataset(n=40)
        a = Pipeline(x, y, 8, seed=7, use_native=use_native)
        b = Pipeline(x, y, 8, seed=7, use_native=use_native)
        for _ in range(10):
            xa, ya = next(a)
            xb, yb = next(b)
            np.testing.assert_array_equal(xa, xb)
            np.testing.assert_array_equal(ya, yb)
        a.close()
        b.close()

    def test_reshuffles_between_passes(self, use_native):
        x, _ = _dataset(n=64)
        y = np.arange(64, dtype=np.int32)
        p = Pipeline(x, y, 64, shuffle=True, seed=1, use_native=use_native)
        _, y1 = next(p)
        _, y2 = next(p)
        assert not np.array_equal(y1, y2)  # different pass permutations
        p.close()

    def test_seek_matches_sequential_consumption(self, use_native):
        x, y = _dataset(n=48)
        a = Pipeline(x, y, 12, seed=6, use_native=use_native)
        for _ in range(7):  # consume into pass 1
            next(a)
        xa, ya = next(a)  # step 7
        b = Pipeline(x, y, 12, seed=6, use_native=use_native)
        b.seek(7)
        xb, yb = next(b)
        np.testing.assert_array_equal(xa, xb)
        np.testing.assert_array_equal(ya, yb)
        assert b.steps_emitted == 8
        a.close()
        b.close()

    def test_next_after_close_raises(self, use_native):
        x, y = _dataset()
        p = Pipeline(x, y, 8, use_native=use_native)
        next(p)
        p.close()
        with pytest.raises(ValueError, match="closed"):
            next(p)

    def test_rejects_bad_inputs(self, use_native):
        x, y = _dataset()
        with pytest.raises(TypeError):
            Pipeline(x.astype(np.float32), y, 8, use_native=use_native)
        with pytest.raises(ValueError):
            Pipeline(x, y, 0, use_native=use_native)
        with pytest.raises(ValueError):
            Pipeline(x, y[:-1], 8, use_native=use_native)


@pytest.mark.skipif(not native_available(), reason="no C++ toolchain")
class TestShuffleUnification:
    """The native and Python implementations consume ONE numpy-computed
    per-pass permutation (the native ring receives it as an index
    buffer), so their streams are bit-identical — the documented
    native-vs-Python divergence is dead. DTPU_NATIVE_LEGACY_SHUFFLE=1
    restores the old C++ splitmix order for experiments pinned to
    pre-unification artifacts."""

    def test_native_matches_python_bit_exact(self):
        x, _ = _dataset(n=60)
        y = np.arange(60, dtype=np.int32)
        nat = Pipeline(x, y, 12, seed=9, use_native=True)
        py = Pipeline(x, y, 12, seed=9, use_native=False)
        assert nat.is_native
        for _ in range(15):  # crosses pass boundaries (re-shuffles)
            xa, ya = next(nat)
            xb, yb = next(py)
            np.testing.assert_array_equal(xa, xb)
            np.testing.assert_array_equal(ya, yb)
        nat.close()
        py.close()

    def test_native_matches_python_after_seek_and_shard(self):
        x, _ = _dataset(n=64)
        y = np.arange(64, dtype=np.int32)
        nat = Pipeline(x, y, 16, seed=2, shard=(1, 2), use_native=True)
        py = Pipeline(x, y, 16, seed=2, shard=(1, 2), use_native=False)
        nat.seek(9)
        py.seek(9)
        for _ in range(6):
            xa, ya = next(nat)
            xb, yb = next(py)
            np.testing.assert_array_equal(xa, xb)
            np.testing.assert_array_equal(ya, yb)
        nat.close()
        py.close()

    def test_legacy_env_flag_restores_old_native_order(self, monkeypatch):
        monkeypatch.setenv("DTPU_NATIVE_LEGACY_SHUFFLE", "1")
        x, _ = _dataset(n=60)
        y = np.arange(60, dtype=np.int32)
        nat = Pipeline(x, y, 12, seed=9, use_native=True)
        py = Pipeline(x, y, 12, seed=9, use_native=False)
        # Legacy native order is the C++ splitmix shuffle — deterministic
        # (two legacy instances agree) but NOT the numpy order.
        nat2 = Pipeline(x, y, 12, seed=9, use_native=True)
        diverged = False
        for _ in range(10):
            xa, ya = next(nat)
            _, ya2 = next(nat2)
            _, yb = next(py)
            np.testing.assert_array_equal(ya, ya2)
            diverged = diverged or not np.array_equal(ya, yb)
        assert diverged  # old order really is different
        # Every pass still covers all rows exactly once.
        nat.seek(0)
        seen = []
        for _ in range(5):
            seen.extend(next(nat)[1].tolist())
        assert sorted(seen) == list(range(60))
        nat.close()
        nat2.close()
        py.close()


@pytest.mark.skipif(not native_available(), reason="no C++ toolchain")
class TestNativeSpecifics:
    def test_prefetch_deeper_than_one_pass(self):
        # depth > steps_per_pass exercises the ring wraparound + pass
        # boundary under concurrency.
        x, y = _dataset(n=32)
        p = Pipeline(x, y, 16, seed=5, prefetch=8, num_threads=4,
                     use_native=True)
        ref = Pipeline(x, y, 16, seed=5, prefetch=1, num_threads=1,
                       use_native=True)
        for _ in range(12):
            xa, ya = next(p)
            xb, yb = next(ref)
            np.testing.assert_array_equal(xa, xb)  # order is thread-invariant
            np.testing.assert_array_equal(ya, yb)
        p.close()
        ref.close()

    def test_close_is_idempotent(self):
        x, y = _dataset()
        p = Pipeline(x, y, 8, use_native=True)
        next(p)
        p.close()
        p.close()

    def test_close_safe_after_failed_handle_creation(self, monkeypatch):
        """A Pipeline whose native handle creation failed partway must
        tear down cleanly: close()/__del__ on the half-constructed
        instance never raises, and never double-destroys — the
        interpreter-shutdown hazard with native prefetch threads live."""
        created = Pipeline.__new__(Pipeline)  # no __init__ at all
        created.close()  # only defensive lookups; must not raise
        created.close()

        def boom(self, start_step):
            raise RuntimeError("dtpu_pipeline_create failed")

        monkeypatch.setattr(Pipeline, "_create_handle", boom)
        x, y = _dataset()
        with pytest.raises(RuntimeError, match="create failed"):
            Pipeline(x, y, 8, use_native=True)
        # __del__ of the failed instance runs at gc with no error (it
        # would print to stderr otherwise); nothing further to assert —
        # the absence of an exception IS the contract.

    def test_seek_failure_leaves_no_dangling_handle(self, monkeypatch):
        """seek() destroys the old native handle before building the new
        one; if the rebuild fails, close() must not destroy the old
        handle a second time."""
        x, y = _dataset()
        p = Pipeline(x, y, 8, use_native=True)
        orig = Pipeline._create_handle

        def boom(self, start_step):
            raise RuntimeError("rebuild failed")

        monkeypatch.setattr(Pipeline, "_create_handle", boom)
        with pytest.raises(RuntimeError, match="rebuild failed"):
            p.seek(3)
        assert p._handle is None  # detached before the failed rebuild
        p.close()  # no double-destroy
        monkeypatch.setattr(Pipeline, "_create_handle", orig)


class TestFitFromPipeline:
    def test_fit_trains_from_iterator(self):
        x, y = _dataset(n=256, shape=(28, 28, 1))
        with Pipeline(x, y, 64, seed=2) as p:
            model = dtpu.Model(dtpu.models.mnist_cnn())
            model.compile(optimizer=dtpu.optim.SGD(0.1),
                          loss="sparse_categorical_crossentropy",
                          metrics=["accuracy"])
            hist = model.fit(p, epochs=3, verbose=0)
        assert len(hist.history["loss"]) == 3
        assert hist.history["loss"][-1] < hist.history["loss"][0]

    def test_resume_fast_forwards_pipeline(self, tmp_path):
        # Crash-restart with a Pipeline source: the resumed run must advance
        # the source past already-consumed batches and finish on the same
        # params as an uninterrupted run.
        from distributed_tpu.training.callbacks import ModelCheckpoint

        x, y = _dataset(n=256, shape=(12, 12, 1))

        def make_model():
            m = dtpu.Model(dtpu.models.mnist_cnn())
            m.compile(optimizer=dtpu.optim.SGD(0.05),
                      loss="sparse_categorical_crossentropy")
            m.build((12, 12, 1), seed=0)
            return m

        with Pipeline(x, y, 64, seed=8, use_native=False) as p1:
            m1 = make_model()
            m1.fit(p1, epochs=4, verbose=0)

        with Pipeline(x, y, 64, seed=8, use_native=False) as p2:
            m2 = make_model()
            m2.fit(p2, epochs=2, verbose=0,
                   callbacks=[ModelCheckpoint(tmp_path, save_freq="epoch")])
        with Pipeline(x, y, 64, seed=8, use_native=False) as p3:  # relaunch
            m3 = make_model()
            m3.fit(p3, epochs=4, verbose=0,
                   callbacks=[ModelCheckpoint(tmp_path, save_freq="epoch",
                                              restore=True)])
        assert m3.step == m1.step
        import jax

        for a, b in zip(jax.tree_util.tree_leaves(m1.params),
                        jax.tree_util.tree_leaves(m3.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_plain_iterator_requires_steps(self):
        model = dtpu.Model(dtpu.models.mnist_cnn())
        model.compile(optimizer=dtpu.optim.SGD(0.1),
                      loss="sparse_categorical_crossentropy")
        with pytest.raises(ValueError, match="steps_per_epoch"):
            model.fit(iter([]), epochs=1)

    def test_non_iterator_without_y_rejected(self):
        model = dtpu.Model(dtpu.models.mnist_cnn())
        model.compile(optimizer=dtpu.optim.SGD(0.1),
                      loss="sparse_categorical_crossentropy")
        with pytest.raises(ValueError, match="batch iterator"):
            model.fit(np.zeros((8, 28, 28, 1)))
