"""Pipeline parallelism: PipelinedBlocks + DataPipelineParallel.

Beyond-reference capability (SURVEY.md §2c "Pipeline parallelism: NO"):
the GPipe microbatch schedule must match single-device numerics exactly
(same stacked params, scan vs schedule), shard one-stage-per-rank, and
train end-to-end through fit/evaluate on the 8-device CPU sim.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec

import distributed_tpu as dtpu
from distributed_tpu import nn

VOCAB = 64


def _lm(num_layers=4, **kw):
    kw.setdefault("d_model", 32)
    kw.setdefault("num_heads", 4)
    kw.setdefault("max_len", 16)
    return dtpu.models.transformer_lm(
        VOCAB, num_layers=num_layers, pipeline=True, **kw
    )


def _copy_task(n, t, seed=0):
    rng = np.random.default_rng(seed)
    starts = rng.integers(0, VOCAB, size=n)
    pos = np.arange(t + 1)[None, :]
    toks = (starts[:, None] + pos) % VOCAB
    return toks[:, :-1].astype(np.int32), toks[:, 1:].astype(np.int32)


def _mlp_block():
    return nn.Sequential(
        [nn.Dense(16, activation="gelu"), nn.Dense(8)], name="main"
    )


class TestPipelinedBlocksLayer:
    def test_scan_matches_unrolled(self):
        layer = nn.PipelinedBlocks(_mlp_block, 3)
        params, state, out = layer.init(jax.random.PRNGKey(0), (8,))
        assert out == (8,)
        assert state == {}
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 8))
        y, _ = layer.apply(params, state, x)
        # unrolled reference: apply each stage's slice in order
        h = x
        block = _mlp_block()
        block.init(jax.random.PRNGKey(0), (8,))  # finalize names
        for i in range(3):
            p_i = jax.tree_util.tree_map(lambda a: a[i], params["blocks"])
            h, _ = block.apply(p_i, {}, h)
        np.testing.assert_allclose(np.asarray(y), np.asarray(h),
                                   rtol=1e-5, atol=1e-6)

    def test_stage_params_differ(self):
        layer = nn.PipelinedBlocks(_mlp_block, 2)
        params, _, _ = layer.init(jax.random.PRNGKey(0), (8,))
        kernel = params["blocks"]["dense"]["kernel"]
        assert not np.allclose(kernel[0], kernel[1])  # distinct stage init

    def test_shape_changing_block_rejected(self):
        bad = lambda: nn.Dense(5)
        with pytest.raises(ValueError, match="preserve shape"):
            nn.PipelinedBlocks(bad, 2).init(jax.random.PRNGKey(0), (8,))

    def test_stateful_block_rejected(self):
        bad = lambda: nn.BatchNorm()
        with pytest.raises(ValueError, match="stateless"):
            nn.PipelinedBlocks(bad, 2).init(jax.random.PRNGKey(0), (8,))

    def test_hints(self):
        layer = nn.PipelinedBlocks(_mlp_block, 2)
        assert layer.sharding_hints() == {"blocks": "pipe"}

    def test_dtype_changing_block_carries(self):
        # bf16-compute blocks in an f32 stream: output cast back to carry
        # dtype, like any mixed-precision layer.
        mk = lambda: nn.Dense(8, dtype=jnp.bfloat16)
        layer = nn.PipelinedBlocks(mk, 2)
        params, state, _ = layer.init(jax.random.PRNGKey(0), (8,))
        y, _ = layer.apply(params, state, jnp.zeros((4, 8), jnp.float32))
        assert y.dtype == jnp.float32

    def test_num_microbatches_validated(self, devices):
        with pytest.raises(ValueError, match="num_microbatches"):
            dtpu.DataPipelineParallel(pipeline_parallel=2, num_microbatches=0)

    def test_dropout_block_trains_under_pp(self, devices):
        mk = lambda: nn.Sequential(
            [nn.Dense(16, activation="gelu"), nn.Dropout(0.1), nn.Dense(8)],
            name="main",
        )
        strategy = dtpu.DataPipelineParallel(pipeline_parallel=2)
        with strategy.scope():
            model = dtpu.Model(nn.Sequential(
                [nn.PipelinedBlocks(mk, 2), nn.Dense(4)]))
            model.compile(optimizer=dtpu.optim.SGD(0.1),
                          loss="sparse_categorical_crossentropy")
        rng = np.random.default_rng(0)
        x = rng.normal(size=(32, 8)).astype(np.float32)
        y = rng.integers(0, 4, size=32).astype(np.int32)
        hist = model.fit(x, y, batch_size=16, epochs=2, verbose=0)
        assert all(np.isfinite(hist.history["loss"]))


class TestDataPipelineParallel:
    def test_param_shardings(self, devices):
        strategy = dtpu.DataPipelineParallel(pipeline_parallel=2)
        with strategy.scope():
            model = dtpu.Model(_lm())
            model.compile(optimizer=dtpu.optim.SGD(0.1),
                          loss="sparse_categorical_crossentropy")
        model.build((16,))
        stacked = model.params["pipelined_blocks"]["blocks"]
        for leaf in jax.tree_util.tree_leaves(stacked):
            assert leaf.sharding.spec[0] == "pipe", leaf.sharding
        # non-pipelined params replicated
        emb = model.params["embedding"]["table"]
        assert emb.sharding.spec == PartitionSpec()

    # pp4 @slow (tier-1 budget, PR 16): each pipeline width compiles its
    # own ~7s program and the parity property is identical; pp2 (the
    # minimal multi-stage schedule) stays in tier-1 — the zigzag-width
    # precedent from PR 10.
    @pytest.mark.parametrize("pp,mb", [
        (2, 2),
        pytest.param(4, 4, marks=pytest.mark.slow),
    ], ids=["pp2", "pp4"])
    def test_pp_matches_single_device(self, devices, pp, mb):
        x, y = _copy_task(64, 16, seed=3)

        def train(strategy):
            def mk():
                m = dtpu.Model(_lm())
                m.compile(optimizer=dtpu.optim.SGD(0.1),
                          loss="sparse_categorical_crossentropy",
                          metrics=["accuracy"])
                return m

            if strategy is None:
                model = mk()
            else:
                with strategy.scope():
                    model = mk()
            hist = model.fit(x, y, batch_size=32, epochs=2, verbose=0,
                             seed=7, shuffle=False)
            return hist.history["loss"]

        ref = train(None)
        pipe = train(dtpu.DataPipelineParallel(
            pipeline_parallel=pp, num_microbatches=mb))
        np.testing.assert_allclose(ref, pipe, rtol=2e-4, atol=2e-5)

    def test_evaluate_under_pp(self, devices):
        strategy = dtpu.DataPipelineParallel(pipeline_parallel=2)
        with strategy.scope():
            model = dtpu.Model(_lm())
            model.compile(optimizer=dtpu.optim.Adam(1e-3),
                          loss="sparse_categorical_crossentropy",
                          metrics=["accuracy"])
        model.build((16,))
        x, y = _copy_task(32, 16, seed=5)
        ref = dtpu.Model(_lm())
        ref.compile(optimizer=dtpu.optim.Adam(1e-3),
                    loss="sparse_categorical_crossentropy",
                    metrics=["accuracy"])
        ref.build((16,))
        want = ref.evaluate(x, y, batch_size=8, verbose=0)
        got = model.evaluate(x, y, batch_size=8, verbose=0)
        assert got["loss"] == pytest.approx(want["loss"], rel=1e-4)

    def test_blocks_not_divisible_by_stages(self, devices):
        strategy = dtpu.DataPipelineParallel(pipeline_parallel=4)
        with strategy.scope():
            model = dtpu.Model(_lm(num_layers=3))
            model.compile(optimizer=dtpu.optim.SGD(0.1),
                          loss="sparse_categorical_crossentropy")
        x, y = _copy_task(32, 16)
        with pytest.raises(ValueError, match="not divisible"):
            model.fit(x, y, batch_size=16, epochs=1, verbose=0)

    def test_batch_not_divisible_by_microbatches(self, devices):
        strategy = dtpu.DataPipelineParallel(
            pipeline_parallel=2, num_microbatches=3)
        with strategy.scope():
            model = dtpu.Model(_lm(num_layers=2))
            model.compile(optimizer=dtpu.optim.SGD(0.1),
                          loss="sparse_categorical_crossentropy")
        x, y = _copy_task(32, 16)
        with pytest.raises(ValueError, match="microbatches"):
            model.fit(x, y, batch_size=16, epochs=1, verbose=0)

    # @slow (tier-1 budget, PR 17): ~7s convergence drive; pipeline
    # numerics stay in-tier via test_pp_matches_single_device[pp2] and
    # copy-task convergence of the same stack stays in-tier via
    # TestTransformerTraining::test_learns_copy_task (test_transformer.py).
    @pytest.mark.slow
    def test_learns_copy_task(self, devices):
        strategy = dtpu.DataPipelineParallel(pipeline_parallel=2)
        with strategy.scope():
            model = dtpu.Model(_lm())
            model.compile(optimizer=dtpu.optim.Adam(1e-2),
                          loss="sparse_categorical_crossentropy",
                          metrics=["accuracy"])
        x, y = _copy_task(256, 16)
        hist = model.fit(x, y, batch_size=64, epochs=6, verbose=0, seed=1)
        assert hist.history["accuracy"][-1] > 0.7, hist.history
