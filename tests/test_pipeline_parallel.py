"""Pipeline parallelism: PipelinedBlocks + DataPipelineParallel.

Beyond-reference capability (SURVEY.md §2c "Pipeline parallelism: NO"):
the GPipe microbatch schedule must match single-device numerics exactly
(same stacked params, scan vs schedule), shard one-stage-per-rank, and
train end-to-end through fit/evaluate on the 8-device CPU sim.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec

import distributed_tpu as dtpu
from distributed_tpu import nn

VOCAB = 64


def _lm(num_layers=4, **kw):
    kw.setdefault("d_model", 32)
    kw.setdefault("num_heads", 4)
    kw.setdefault("max_len", 16)
    return dtpu.models.transformer_lm(
        VOCAB, num_layers=num_layers, pipeline=True, **kw
    )


def _copy_task(n, t, seed=0):
    rng = np.random.default_rng(seed)
    starts = rng.integers(0, VOCAB, size=n)
    pos = np.arange(t + 1)[None, :]
    toks = (starts[:, None] + pos) % VOCAB
    return toks[:, :-1].astype(np.int32), toks[:, 1:].astype(np.int32)


def _mlp_block():
    return nn.Sequential(
        [nn.Dense(16, activation="gelu"), nn.Dense(8)], name="main"
    )


class TestPipelinedBlocksLayer:
    def test_scan_matches_unrolled(self):
        layer = nn.PipelinedBlocks(_mlp_block, 3)
        params, state, out = layer.init(jax.random.PRNGKey(0), (8,))
        assert out == (8,)
        assert state == {}
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 8))
        y, _ = layer.apply(params, state, x)
        # unrolled reference: apply each stage's slice in order
        h = x
        block = _mlp_block()
        block.init(jax.random.PRNGKey(0), (8,))  # finalize names
        for i in range(3):
            p_i = jax.tree_util.tree_map(lambda a: a[i], params["blocks"])
            h, _ = block.apply(p_i, {}, h)
        np.testing.assert_allclose(np.asarray(y), np.asarray(h),
                                   rtol=1e-5, atol=1e-6)

    def test_stage_params_differ(self):
        layer = nn.PipelinedBlocks(_mlp_block, 2)
        params, _, _ = layer.init(jax.random.PRNGKey(0), (8,))
        kernel = params["blocks"]["dense"]["kernel"]
        assert not np.allclose(kernel[0], kernel[1])  # distinct stage init

    def test_shape_changing_block_rejected(self):
        bad = lambda: nn.Dense(5)
        with pytest.raises(ValueError, match="preserve shape"):
            nn.PipelinedBlocks(bad, 2).init(jax.random.PRNGKey(0), (8,))

    def test_stateful_block_rejected(self):
        bad = lambda: nn.BatchNorm()
        with pytest.raises(ValueError, match="stateless"):
            nn.PipelinedBlocks(bad, 2).init(jax.random.PRNGKey(0), (8,))

    def test_hints(self):
        layer = nn.PipelinedBlocks(_mlp_block, 2)
        assert layer.sharding_hints() == {"blocks": "pipe"}

    def test_dtype_changing_block_carries(self):
        # bf16-compute blocks in an f32 stream: output cast back to carry
        # dtype, like any mixed-precision layer.
        mk = lambda: nn.Dense(8, dtype=jnp.bfloat16)
        layer = nn.PipelinedBlocks(mk, 2)
        params, state, _ = layer.init(jax.random.PRNGKey(0), (8,))
        y, _ = layer.apply(params, state, jnp.zeros((4, 8), jnp.float32))
        assert y.dtype == jnp.float32

    def test_num_microbatches_validated(self, devices):
        with pytest.raises(ValueError, match="num_microbatches"):
            dtpu.DataPipelineParallel(pipeline_parallel=2, num_microbatches=0)

    def test_dropout_block_trains_under_pp(self, devices):
        mk = lambda: nn.Sequential(
            [nn.Dense(16, activation="gelu"), nn.Dropout(0.1), nn.Dense(8)],
            name="main",
        )
        strategy = dtpu.DataPipelineParallel(pipeline_parallel=2)
        with strategy.scope():
            model = dtpu.Model(nn.Sequential(
                [nn.PipelinedBlocks(mk, 2), nn.Dense(4)]))
            model.compile(optimizer=dtpu.optim.SGD(0.1),
                          loss="sparse_categorical_crossentropy")
        rng = np.random.default_rng(0)
        x = rng.normal(size=(32, 8)).astype(np.float32)
        y = rng.integers(0, 4, size=32).astype(np.int32)
        hist = model.fit(x, y, batch_size=16, epochs=2, verbose=0)
        assert all(np.isfinite(hist.history["loss"]))


class TestDataPipelineParallel:
    def test_param_shardings(self, devices):
        strategy = dtpu.DataPipelineParallel(pipeline_parallel=2)
        with strategy.scope():
            model = dtpu.Model(_lm())
            model.compile(optimizer=dtpu.optim.SGD(0.1),
                          loss="sparse_categorical_crossentropy")
        model.build((16,))
        stacked = model.params["pipelined_blocks"]["blocks"]
        for leaf in jax.tree_util.tree_leaves(stacked):
            assert leaf.sharding.spec[0] == "pipe", leaf.sharding
        # non-pipelined params replicated
        emb = model.params["embedding"]["table"]
        assert emb.sharding.spec == PartitionSpec()

    # pp4 @slow (tier-1 budget, PR 16): each pipeline width compiles its
    # own ~7s program and the parity property is identical; pp2 @slow
    # too since PR 19 — TestInterleavedSchedule::
    # test_parity_bubble_and_telemetry pins the SAME pp2 gpipe-vs-
    # single-device parity at the tighter rtol 2e-5 in-tier, so this
    # cell's coverage is retained there (and here via -m slow /
    # TIER1_PIPELINE_SMOKE when touching the schedule).
    @pytest.mark.slow
    @pytest.mark.parametrize("pp,mb", [
        (2, 2),
        (4, 4),
    ], ids=["pp2", "pp4"])
    def test_pp_matches_single_device(self, devices, pp, mb):
        x, y = _copy_task(64, 16, seed=3)

        def train(strategy):
            def mk():
                m = dtpu.Model(_lm())
                m.compile(optimizer=dtpu.optim.SGD(0.1),
                          loss="sparse_categorical_crossentropy",
                          metrics=["accuracy"])
                return m

            if strategy is None:
                model = mk()
            else:
                with strategy.scope():
                    model = mk()
            hist = model.fit(x, y, batch_size=32, epochs=2, verbose=0,
                             seed=7, shuffle=False)
            return hist.history["loss"]

        ref = train(None)
        pipe = train(dtpu.DataPipelineParallel(
            pipeline_parallel=pp, num_microbatches=mb))
        np.testing.assert_allclose(ref, pipe, rtol=2e-4, atol=2e-5)

    def test_evaluate_under_pp(self, devices):
        strategy = dtpu.DataPipelineParallel(pipeline_parallel=2)
        with strategy.scope():
            model = dtpu.Model(_lm())
            model.compile(optimizer=dtpu.optim.Adam(1e-3),
                          loss="sparse_categorical_crossentropy",
                          metrics=["accuracy"])
        model.build((16,))
        x, y = _copy_task(32, 16, seed=5)
        ref = dtpu.Model(_lm())
        ref.compile(optimizer=dtpu.optim.Adam(1e-3),
                    loss="sparse_categorical_crossentropy",
                    metrics=["accuracy"])
        ref.build((16,))
        want = ref.evaluate(x, y, batch_size=8, verbose=0)
        got = model.evaluate(x, y, batch_size=8, verbose=0)
        assert got["loss"] == pytest.approx(want["loss"], rel=1e-4)

    def test_blocks_not_divisible_by_stages(self, devices):
        strategy = dtpu.DataPipelineParallel(pipeline_parallel=4)
        with strategy.scope():
            model = dtpu.Model(_lm(num_layers=3))
            model.compile(optimizer=dtpu.optim.SGD(0.1),
                          loss="sparse_categorical_crossentropy")
        x, y = _copy_task(32, 16)
        with pytest.raises(ValueError, match="not divisible"):
            model.fit(x, y, batch_size=16, epochs=1, verbose=0)

    def test_batch_not_divisible_by_microbatches(self, devices):
        strategy = dtpu.DataPipelineParallel(
            pipeline_parallel=2, num_microbatches=3)
        with strategy.scope():
            model = dtpu.Model(_lm(num_layers=2))
            model.compile(optimizer=dtpu.optim.SGD(0.1),
                          loss="sparse_categorical_crossentropy")
        x, y = _copy_task(32, 16)
        with pytest.raises(ValueError, match="microbatches"):
            model.fit(x, y, batch_size=16, epochs=1, verbose=0)

    # @slow (tier-1 budget, PR 17): ~7s convergence drive; pipeline
    # numerics stay in-tier via TestInterleavedSchedule::
    # test_parity_bubble_and_telemetry (rtol 2e-5, since PR 19) and
    # copy-task convergence of the same stack stays in-tier via
    # TestTransformerTraining::test_learns_copy_task (test_transformer.py).
    @pytest.mark.slow
    def test_learns_copy_task(self, devices):
        strategy = dtpu.DataPipelineParallel(pipeline_parallel=2)
        with strategy.scope():
            model = dtpu.Model(_lm())
            model.compile(optimizer=dtpu.optim.Adam(1e-2),
                          loss="sparse_categorical_crossentropy",
                          metrics=["accuracy"])
        x, y = _copy_task(256, 16)
        hist = model.fit(x, y, batch_size=64, epochs=6, verbose=0, seed=1)
        assert hist.history["accuracy"][-1] > 0.7, hist.history


class TestInterleavedSchedule:
    """The virtual-stage schedule: each pipe rank holds ``interleave``
    non-contiguous stage chunks and activations circulate ``interleave``
    laps over the full ring, shrinking the bubble from (n-1)/(M+n-1) to
    (n-1)/(vM+n-1) at the SAME microbatch count."""

    def test_schedule_validation(self):
        with pytest.raises(ValueError, match="schedule"):
            nn.PipelinedBlocks(_mlp_block, 4, schedule="zigzag")
        with pytest.raises(ValueError, match="interleave"):
            nn.PipelinedBlocks(_mlp_block, 4, schedule="gpipe",
                               interleave=2)
        with pytest.raises(ValueError, match="interleave"):
            nn.PipelinedBlocks(_mlp_block, 4, schedule="interleaved",
                               interleave=1)

    def test_blocks_divisible_by_stages_times_interleave(self, devices):
        # 6 blocks cannot chunk into 2 ranks x 2 virtual stages.
        strategy = dtpu.DataPipelineParallel(pipeline_parallel=2)
        with strategy.scope():
            model = dtpu.Model(_lm(num_layers=6,
                                   pipeline_schedule="interleaved",
                                   pipeline_interleave=2))
            model.compile(optimizer=dtpu.optim.SGD(0.1),
                          loss="sparse_categorical_crossentropy")
        x, y = _copy_task(32, 16)
        with pytest.raises(ValueError, match="not divisible"):
            model.fit(x, y, batch_size=16, epochs=1, verbose=0)

    def test_microbatches_must_cover_stages(self, devices):
        # v > 1 re-injects lap outputs at rank 0 slot (t - n) mod M, which
        # needs M >= n — fewer microbatches than ranks must raise loudly.
        strategy = dtpu.DataPipelineParallel(pipeline_parallel=4,
                                             num_microbatches=2)
        with strategy.scope():
            model = dtpu.Model(_lm(num_layers=8,
                                   pipeline_schedule="interleaved",
                                   pipeline_interleave=2))
            model.compile(optimizer=dtpu.optim.SGD(0.1),
                          loss="sparse_categorical_crossentropy")
        x, y = _copy_task(32, 16)
        with pytest.raises(ValueError, match="num_microbatches"):
            model.fit(x, y, batch_size=16, epochs=1, verbose=0)

    def _train(self, schedule, interleave, *, strategy, grad_accum=1,
               precision=None, x=None, y=None):
        def mk():
            m = dtpu.Model(_lm(pipeline_schedule=schedule,
                               pipeline_interleave=interleave))
            m.compile(optimizer=dtpu.optim.SGD(0.1),
                      loss="sparse_categorical_crossentropy",
                      precision=precision)
            return m

        if strategy is None:
            model = mk()
        else:
            with strategy.scope():
                model = mk()
        hist = model.fit(x, y, batch_size=32, epochs=2, verbose=0, seed=7,
                         shuffle=False, grad_accum=grad_accum)
        return hist.history["loss"], model

    def test_parity_bubble_and_telemetry(self, devices, tmp_path,
                                         monkeypatch):
        """The tentpole's acceptance triple in one compile budget: the
        interleaved schedule's loss trajectory matches gpipe AND the
        single-device sequential path at rtol 2e-5; its telemetry bubble
        is strictly below gpipe's at the same M; and the fit emits the
        schedule/bubble events with the declared keys."""
        monkeypatch.setenv("DTPU_EVENT_LOG",
                           str(tmp_path / "events.jsonl"))
        x, y = _copy_task(64, 16, seed=3)
        ref, _ = self._train("gpipe", 1, strategy=None, x=x, y=y)
        gp, m_gp = self._train(
            "gpipe", 1, x=x, y=y,
            strategy=dtpu.DataPipelineParallel(pipeline_parallel=2,
                                               num_microbatches=4))
        il, m_il = self._train(
            "interleaved", 2, x=x, y=y,
            strategy=dtpu.DataPipelineParallel(pipeline_parallel=2,
                                               num_microbatches=4))
        np.testing.assert_allclose(gp, ref, rtol=2e-5)
        np.testing.assert_allclose(il, ref, rtol=2e-5)
        tg = m_gp.last_fit_telemetry["pipeline"]
        ti = m_il.last_fit_telemetry["pipeline"]
        assert tg == {"schedule": "gpipe", "interleave": 1, "num_stages": 2,
                      "num_microbatches": 4, "ticks": 5,
                      "bubble_fraction": 0.2}
        assert ti == {"schedule": "interleaved", "interleave": 2,
                      "num_stages": 2, "num_microbatches": 4, "ticks": 9,
                      "bubble_fraction": round(1 / 9, 6)}
        assert ti["bubble_fraction"] < tg["bubble_fraction"]
        import json as _json
        rows = [_json.loads(l) for l in
                (tmp_path / "events.jsonl").read_text().splitlines()]
        sched = [r for r in rows
                 if r["event"] == "pipeline_schedule_selected"]
        bub = [r for r in rows if r["event"] == "bubble_report"]
        assert {s["schedule"] for s in sched} == {"gpipe", "interleaved"}
        assert {b["bubble_fraction"] for b in bub} == {0.2, round(1 / 9, 6)}

    # Heavy matrix cells @slow (tier-1 budget): each is another pair of
    # ~5s pipeline compiles and the parity property is the one the base
    # cell above already pins; grad_accum and precision only re-route the
    # SAME schedule through the accumulation scan / cast policy.
    @pytest.mark.slow
    @pytest.mark.parametrize("grad_accum,precision,rtol", [
        (2, None, 2e-5),
        # bf16 compute reorders reductions between the schedules, so the
        # parity band is the compute dtype's, not f32's.
        (1, "mixed_bfloat16", 2e-2),
    ], ids=["accum2", "bf16"])
    def test_parity_matrix_heavy(self, devices, grad_accum, precision,
                                 rtol):
        x, y = _copy_task(64, 16, seed=3)
        gp, _ = self._train(
            "gpipe", 1, x=x, y=y, grad_accum=grad_accum,
            precision=precision,
            strategy=dtpu.DataPipelineParallel(pipeline_parallel=2,
                                               num_microbatches=4))
        il, _ = self._train(
            "interleaved", 2, x=x, y=y, grad_accum=grad_accum,
            precision=precision,
            strategy=dtpu.DataPipelineParallel(pipeline_parallel=2,
                                               num_microbatches=4))
        np.testing.assert_allclose(il, gp, rtol=rtol)
