"""Mixed-precision policies (ISSUE 5): bf16/f16 compute over f32 masters.

The contract under test: a ``compile(precision=...)`` policy changes the
dtype the forward/backward COMPUTES in, never where the truth lives —
params and optimizer state stay float32 master weights, gradients come
back f32 through the cast's VJP, accumulation stays f32, and checkpoints
persist the masters so f32<->mixed round-trips are exact. Loss curves
under ``mixed_bfloat16`` track the f32 reference to bf16 rounding
(measured max rel diff ~5e-4 over 10 steps on this config; the 5e-3
tolerance is 10x slack), identically across every data-parallel strategy.
``mixed_float16`` adds dynamic loss scaling; the skip-step path is
exercised both at the optax-transform level (injected inf gradient) and
end-to-end (an overflowing initial scale must halve per step while params
stay untouched). Small and short throughout: tier-1 has ~40s of headroom.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import distributed_tpu as dtpu
from distributed_tpu import optim, precision

VOCAB, T, B = 64, 16, 8


def _data(n=128, seed=3):
    rng = np.random.default_rng(seed)
    tok = rng.integers(0, VOCAB, (n, T + 1), dtype=np.int64)
    return tok[:, :-1].astype(np.int32), tok[:, 1:].astype(np.int32)


def _lm(strategy, **compile_kw):
    with strategy.scope():
        m = dtpu.Model(dtpu.models.transformer_lm(
            VOCAB, num_layers=1, d_model=32, num_heads=2, max_len=T))
        m.compile(optimizer=dtpu.optim.Adam(1e-3),
                  loss="sparse_categorical_crossentropy", **compile_kw)
    return m


def _step_losses(model, x, y, steps=10, **fit_kw):
    losses = []
    cb = dtpu.callbacks.LambdaCallback(
        on_batch_end=lambda m, s, logs: losses.append(float(logs["loss"]))
    )
    model.fit(x, y, batch_size=B, epochs=1, steps_per_epoch=steps,
              verbose=0, seed=5, shuffle=False, callbacks=[cb], **fit_kw)
    return np.asarray(losses)


def _assert_f32_masters(model):
    """Params AND optimizer state are f32 masters regardless of policy."""
    for leaf in jax.tree_util.tree_leaves((model.params, model.opt_state)):
        if jnp.issubdtype(jnp.result_type(leaf), jnp.floating):
            assert jnp.result_type(leaf) == jnp.float32, leaf.dtype


@pytest.fixture(scope="module")
def two_dev(devices):
    return devices[:2]


@pytest.fixture(scope="module")
def lm_data():
    return _data()


@pytest.fixture(scope="module")
def f32_run(two_dev, lm_data):
    """f32 reference (no policy at all — the pre-policy default path):
    per-step losses over 10 steps. Strategies are loss-identical at f32
    (test_zero pins that at ULP level), so one reference serves every
    mixed-vs-f32 comparison."""
    x, y = lm_data
    m = _lm(dtpu.DataParallel(devices=two_dev))
    return _step_losses(m, x, y)


# ---------------------------------------------------------------- policy unit
class TestPolicy:
    def test_presets(self):
        p = dtpu.Policy("mixed_bfloat16")
        assert p.param_dtype == jnp.float32
        assert p.compute_dtype == jnp.bfloat16
        assert p.output_dtype == jnp.float32
        assert not p.loss_scaling  # bf16 keeps f32's exponent range
        assert dtpu.Policy("mixed_float16").loss_scaling
        f32 = dtpu.Policy("float32")
        assert not f32.needs_compute_cast

    def test_get(self):
        assert precision.get(None) is None
        p = dtpu.Policy("mixed_bfloat16")
        assert precision.get(p) is p
        assert precision.get("mixed_bfloat16").compute_dtype == jnp.bfloat16
        with pytest.raises(ValueError, match="bfloat16"):
            precision.get("bf16_but_misspelled")
        with pytest.raises(TypeError, match="Policy"):
            precision.get(7)

    def test_resolve_dtype_scope_and_override(self):
        assert precision.resolve_dtype(None) is None
        with dtpu.Policy("mixed_bfloat16").scope():
            assert precision.resolve_dtype(None) == jnp.bfloat16
            # an explicit per-layer dtype= always wins over the policy
            assert precision.resolve_dtype(jnp.float32) == jnp.float32
        assert precision.current_policy() is None  # scope restored

    def test_cast_to_compute_respects_hints_and_ints(self):
        p = dtpu.Policy("mixed_bfloat16")
        tree = {"a": {"kernel": jnp.ones((2, 2), jnp.float32)},
                "pinned": {"kernel": jnp.ones((2, 2), jnp.float32)},
                "steps": jnp.zeros((), jnp.int32)}
        cast = p.cast_to_compute(tree, {"pinned": jnp.float32})
        assert cast["a"]["kernel"].dtype == jnp.bfloat16
        assert cast["pinned"]["kernel"].dtype == jnp.float32  # layer's own
        assert cast["steps"].dtype == jnp.int32  # non-floating untouched

    def test_grad_accum_helpers(self):
        params = {"w": jnp.ones((2,), jnp.bfloat16),
                  "n": jnp.zeros((), jnp.int32)}
        acc = precision.grad_accum_init(params)
        assert acc["w"].dtype == jnp.float32  # f32 even for bf16 grads
        assert acc["n"].dtype == jnp.int32
        precision.assert_f32_accumulator(acc)
        with pytest.raises(AssertionError, match="float32"):
            precision.assert_f32_accumulator({"w": jnp.zeros(2, jnp.bfloat16)})
        back = precision.cast_like(acc, params)
        assert back["w"].dtype == jnp.bfloat16


# ------------------------------------------------------- policy x strategy --
STRATEGIES = ["single", "dp", "zero1", "fsdp"]


def _strategy(name, two_dev):
    return {
        "single": lambda: dtpu.SingleDevice(),
        "dp": lambda: dtpu.DataParallel(devices=two_dev),
        "zero1": lambda: dtpu.ZeroDataParallel(devices=two_dev),
        "fsdp": lambda: dtpu.FSDP(devices=two_dev),
    }[name]()


class TestLossParity:
    @pytest.mark.parametrize("strat", STRATEGIES)
    def test_mixed_bfloat16_tracks_f32(self, strat, two_dev, lm_data,
                                       f32_run):
        """bf16 compute over f32 masters: the loss curve matches the f32
        reference to bf16 rounding on EVERY strategy — the policy is a
        compute-dtype lever, orthogonal to where state lives. The FSDP
        case also checks the fit telemetry: the policy name lands in it
        and the collective-byte estimate counts bytes at the dtype they
        MOVE in — the per-layer param all-gathers are exactly half under
        bf16 (every gathered leaf is floating)."""
        x, y = lm_data
        m = _lm(_strategy(strat, two_dev), precision="mixed_bfloat16")
        losses = _step_losses(m, x, y)
        np.testing.assert_allclose(losses, f32_run, rtol=5e-3)
        _assert_f32_masters(m)
        if strat == "fsdp":
            tele = m.last_fit_telemetry
            assert tele["precision"] == "mixed_bfloat16"
            mixed = tele["comm_bytes_estimate"]
            f32 = m.strategy.comm_bytes_estimate(m.params)  # master dtype
            assert mixed["gathered_param_bytes_per_device"] > 0
            assert (f32["gathered_param_bytes_per_device"]
                    == 2 * mixed["gathered_param_bytes_per_device"])
            assert (f32["grad_reduce_bytes_per_device"]
                    == 2 * mixed["grad_reduce_bytes_per_device"])


class TestComposition:
    # @slow (tier-1 budget, PR 17): ~6s composition cross-product; loss
    # parity per strategy stays in-tier (TestLossParity) as does plain
    # grad_accum (test_zero.py) — this pins only their product.
    @pytest.mark.slow
    def test_grad_accum_under_mixed(self, two_dev, lm_data, f32_run):
        """fit(grad_accum=2) under bf16: microbatch grads arrive bf16-
        computed but accumulate in f32 (the in-jit assert in
        _accum_train_step_body enforces it at trace time), so the curve
        still tracks the f32 reference."""
        x, y = lm_data
        m = _lm(dtpu.DataParallel(devices=two_dev),
                precision="mixed_bfloat16")
        losses = _step_losses(m, x, y, grad_accum=2)
        np.testing.assert_allclose(losses, f32_run, rtol=5e-3)
        _assert_f32_masters(m)

    # @slow (tier-1 budget, PR 17): ~7s composition cross-product; loss
    # parity per strategy stays in-tier (TestLossParity) as does plain
    # steps_per_execution (test_multi_step.py) — product only here.
    @pytest.mark.slow
    def test_steps_per_execution_under_mixed(self, two_dev, lm_data,
                                             f32_run):
        """K=2 fused dispatch composes: the multi-step scan casts inside
        each fused step, epoch loss matches the reference mean. (K=2, not
        larger: the scan unrolls fully on XLA:CPU, so compile time scales
        with K — tier-1 budget.)"""
        x, y = lm_data
        m = _lm(dtpu.DataParallel(devices=two_dev),
                precision="mixed_bfloat16", steps_per_execution=2)
        h = m.fit(x, y, batch_size=B, epochs=1, steps_per_epoch=10,
                  verbose=0, seed=5, shuffle=False)
        assert np.isclose(h.history["loss"][0], f32_run.mean(), rtol=5e-3)
        assert m.step == 10


# ------------------------------------------------------------- loss scaling --
class TestLossScaling:
    def _tx(self, **kw):
        return optim.dynamic_loss_scaling(optax.sgd(0.1), **kw)

    def test_finite_step_applies_unscaled(self):
        tx = self._tx(init_scale=8.0)
        params = {"w": jnp.ones((3,), jnp.float32)}
        state = tx.init(params)
        assert float(state.scale) == 8.0
        grads = {"w": jnp.full((3,), 2.0 * 8.0)}  # SCALED by the step body
        updates, state = jax.jit(tx.update)(grads, state, params)
        # sgd(0.1) on the unscaled gradient 2.0
        np.testing.assert_allclose(np.asarray(updates["w"]), -0.2, rtol=1e-6)
        assert float(state.scale) == 8.0

    def test_nonfinite_skips_and_halves(self):
        tx = self._tx(init_scale=8.0)
        params = {"w": jnp.ones((3,), jnp.float32)}
        state = tx.init(params)
        inner0 = jax.device_get(state.inner_state)
        grads = {"w": jnp.array([1.0, jnp.inf, 1.0])}
        updates, state = jax.jit(tx.update)(grads, state, params)
        np.testing.assert_array_equal(np.asarray(updates["w"]), 0.0)
        assert float(state.scale) == 4.0  # halved
        assert int(state.growth_count) == 0
        # the wrapped transform's state was NOT advanced by the bad step
        for a, b in zip(jax.tree_util.tree_leaves(inner0),
                        jax.tree_util.tree_leaves(
                            jax.device_get(state.inner_state))):
            np.testing.assert_array_equal(a, b)

    def test_growth_after_interval(self):
        tx = self._tx(init_scale=4.0, growth_interval=2)
        params = {"w": jnp.ones((2,), jnp.float32)}
        state = tx.init(params)
        good = {"w": jnp.ones((2,), jnp.float32)}
        _, state = tx.update(good, state, params)
        assert float(state.scale) == 4.0 and int(state.growth_count) == 1
        _, state = tx.update(good, state, params)
        assert float(state.scale) == 8.0 and int(state.growth_count) == 0

    def test_loss_scale_value(self):
        tx = self._tx()
        state = tx.init({"w": jnp.ones(2)})
        assert optim.loss_scale_value(state) is state.scale
        assert optim.loss_scale_value(optax.sgd(0.1).init({"w": jnp.ones(2)})
                                      ) is None

    def test_f16_overflow_skips_step_end_to_end(self, two_dev, lm_data):
        """Injected overflow through the REAL jitted train path: an
        initial scale of 2^126 makes scale*loss overflow f32 (and the f16
        backward overflow regardless), so every step must take the skip
        branch — zero updates (params bit-identical to init), scale
        halved per step."""
        x, y = lm_data
        pol = dtpu.Policy("mixed_float16")
        pol.initial_loss_scale = 2.0 ** 126
        m = _lm(dtpu.DataParallel(devices=two_dev), precision=pol)
        m.build((T,), seed=1)
        p0 = jax.device_get(m.params)
        losses = _step_losses(m, x, y, steps=4)
        assert np.all(np.isfinite(losses))  # reported loss is pre-scale
        scale = float(jax.device_get(optim.loss_scale_value(m.opt_state)))
        assert scale == 2.0 ** 122  # halved on each of the 4 steps
        for a, b in zip(jax.tree_util.tree_leaves(p0),
                        jax.tree_util.tree_leaves(jax.device_get(m.params))):
            np.testing.assert_array_equal(a, b)


# --------------------------------------------------------------- checkpoint --
class TestCheckpointRoundTrip:
    # @slow (tier-1 budget, PR 17): ~7s cast-roundtrip drive; the
    # mixed-tracks-f32 loss-parity tests stay in-tier, and the
    # TIER1_PRECISION_SMOKE fast path (no marker filter) still runs this.
    @pytest.mark.slow
    def test_mixed_to_f32_and_back(self, two_dev, lm_data, tmp_path):
        """Checkpoints hold the f32 masters, so save-under-mixed /
        restore-under-f32 (and the reverse) is EXACT — same bytes, same
        step cursor, training continues."""
        x, y = lm_data
        m = _lm(dtpu.DataParallel(devices=two_dev),
                precision="mixed_bfloat16")
        m.fit(x, y, batch_size=B, epochs=1, steps_per_epoch=2, verbose=0,
              seed=0)
        ck = dtpu.Checkpointer(tmp_path / "a")
        ck.save(m)

        m2 = _lm(dtpu.DataParallel(devices=two_dev), precision="float32")
        assert ck.restore_into(m2) == 2
        for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(m.params)),
                        jax.tree_util.tree_leaves(jax.device_get(m2.params))):
            np.testing.assert_array_equal(a, b)
        _assert_f32_masters(m2)
        m2.fit(x, y, batch_size=B, epochs=1, steps_per_epoch=1, verbose=0,
               seed=0)
        assert m2.step == 3

        # And the reverse direction: f32 save -> mixed restore is the
        # same masters, placed and castable (no extra fit needed — the
        # mixed train path is exercised throughout this file).
        ck2 = dtpu.Checkpointer(tmp_path / "b")
        ck2.save(m2)
        m3 = _lm(dtpu.DataParallel(devices=two_dev),
                 precision="mixed_bfloat16")
        assert ck2.restore_into(m3) == 3
        for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(m2.params)),
                        jax.tree_util.tree_leaves(jax.device_get(m3.params))):
            np.testing.assert_array_equal(a, b)

    def test_f16_loss_scale_and_lr_survive(self, two_dev, lm_data, f32_run,
                                           tmp_path):
        """The live loss scale is optimizer state (LossScaleState is a
        pytree NamedTuple), so it checkpoints leaf-for-leaf; and the
        wrapper stays transparent to set_hyperparam — a runtime LR change
        round-trips through it. The training run doubles as the f16
        happy-path check: at the default 2^15 scale nothing overflows on
        this model, every step applies (scaled then exactly unscaled
        grads — pure dtype rounding remains), and the losses track the
        f32 reference."""
        x, y = lm_data
        m = _lm(dtpu.DataParallel(devices=two_dev),
                precision="mixed_float16")
        losses = _step_losses(m, x, y, steps=2)
        np.testing.assert_allclose(losses, f32_run[:2], rtol=5e-3)
        _assert_f32_masters(m)
        m.set_learning_rate(3.3e-4)
        ck = dtpu.Checkpointer(tmp_path)
        ck.save(m)
        m2 = _lm(dtpu.DataParallel(devices=two_dev),
                 precision="mixed_float16")
        ck.restore_into(m2)
        assert float(jax.device_get(optim.loss_scale_value(m2.opt_state))
                     ) == 2.0 ** 15
        assert abs(m2.get_learning_rate() - 3.3e-4) < 1e-9


# ----------------------------------------------------------------- generate --
class TestGenerate:
    def test_bf16_policy_greedy_parity_and_cache_dtype(self, lm_data):
        """Same seed -> same f32 masters; greedy decode under the bf16
        policy emits the SAME tokens as f32 on this model, and the KV
        cache dtype comes from the policy (no abstract trace). Also the
        model-boundary output cast: predict() under a mixed policy hands
        back output_dtype (f32) — downstream numpy never sees bf16."""
        prompt = np.array([[5, 9, 2]], np.int32)
        f32 = _lm(dtpu.SingleDevice())
        f32.build((T,), seed=7)
        mix = _lm(dtpu.SingleDevice(), precision="mixed_bfloat16")
        mix.build((T,), seed=7)
        want = f32.generate(prompt, 8, temperature=0.0)
        got = mix.generate(prompt, 8, temperature=0.0)
        np.testing.assert_array_equal(want, got)
        assert f32._decode_dtype == jnp.float32
        assert mix._decode_dtype == jnp.bfloat16
        out = mix.predict(lm_data[0][:B], batch_size=B)
        assert out.dtype == np.float32


# ---------------------------------------------------- per-layer dtype= wins --
class TestPerLayerOverride:
    def test_explicit_dtype_layer_keeps_master_precision(self, lm_data):
        """A layer constructed with dtype=f32 under a bf16 policy: its
        params are EXEMPT from the policy cast (dtype_hints), so it
        computes from full-precision masters while its neighbors run
        bf16 — per-layer dtype= overrides the policy exactly."""
        x, y = lm_data
        seq = dtpu.nn.Sequential([
            dtpu.nn.Embedding(VOCAB, 32, name="emb"),
            dtpu.nn.Dense(32, activation="relu", dtype=jnp.float32,
                          name="pinned"),
            dtpu.nn.Dense(VOCAB, name="head"),
        ])
        with dtpu.SingleDevice().scope():
            m = dtpu.Model(seq)
            m.compile(optimizer=dtpu.optim.Adam(1e-3),
                      loss="sparse_categorical_crossentropy",
                      precision="mixed_bfloat16")
        m.build((T,))
        assert m._dtype_hints == {"pinned": jnp.float32}
        cast = m.precision.cast_to_compute(m.params, m._dtype_hints)
        assert cast["emb"]["table"].dtype == jnp.bfloat16
        assert cast["head"]["kernel"].dtype == jnp.bfloat16
        assert cast["pinned"]["kernel"].dtype == jnp.float32
        m.fit(x, y, batch_size=B, epochs=1, steps_per_epoch=1, verbose=0,
              seed=0)
        _assert_f32_masters(m)
