"""Serving memory economy: prefix KV sharing, int8 KV, speculative decode.

Three levers, one correctness bar: the engine's greedy token stream must
stay EXACTLY ``generate()``'s whatever blocks are shared (prefix store,
copy-on-write), however the verify dispatch batches candidates
(speculative decoding), and across preemption/requeue and weight swaps.
int8 KV is the one deliberate exception — quantized storage is
fidelity-GATED, not bit-exact, and its test pins the agreement level and
the byte ratio instead.

Kept lean (tier-1 runs on a 1-core box): one tiny LM fixture shared
across the module, every property at the smallest shape that can catch
its failure mode.
"""

import jax
import numpy as np
import pytest

from conftest import assert_no_recompile

import distributed_tpu as dtpu
from distributed_tpu.serving import Engine, PagedKVCache, Request
from distributed_tpu.serving.kv_cache import (
    BlockAllocator, PrefixStore, _chain_hashes,
)


@pytest.fixture(scope="module")
def lm():
    model = dtpu.Model(dtpu.models.transformer_lm(
        32, num_layers=2, d_model=16, num_heads=2, max_len=64))
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    model.build((16,))
    return model


@pytest.fixture(scope="module")
def draft_lm(lm):
    """A 1-layer draft with the target's embedding/head: cheap, wrong
    often — exactly what the exactness contract must survive."""
    model = dtpu.Model(dtpu.models.transformer_lm(
        32, num_layers=1, d_model=16, num_heads=2, max_len=64))
    model.build((16,))
    for name in ("embedding", "positional_embedding", "dense",
                 "layer_norm"):
        if name in model.params and name in lm.params:
            model.params[name] = lm.params[name]
    return model


def _shared_prefix_requests(rng, shared_len=16, n=4, tail=(1, 5),
                            news=(4, 8), vocab=32):
    shared = rng.integers(0, vocab, (shared_len,)).astype(np.int32)
    prompts = [
        np.concatenate([shared, rng.integers(
            0, vocab, (int(t),)).astype(np.int32)])
        for t in rng.integers(*tail, n)
    ]
    return prompts, [int(m) for m in rng.integers(*news, n)]


def _sequential_generate(model, prompts, news):
    return [model.generate(p[None], m, temperature=0.0)[0]
            for p, m in zip(prompts, news)]


# ------------------------------------------------------------- allocator --
def test_allocator_refcounts_and_loud_misuse():
    """allocate -> refcount 1; incref/decref move it; ``free`` refuses
    both double-frees and shared blocks (a freed-while-shared block
    would hand storage still being read to the next allocation)."""
    a = BlockAllocator(8)
    (b,) = a.allocate(1)
    assert a.refcount(b) == 1
    a.incref([b])
    assert a.refcount(b) == 2
    with pytest.raises(ValueError, match="shared block"):
        a.free([b])
    assert a.decref([b]) == 0  # drops to refcount 1, nothing freed
    assert a.decref([b]) == 1  # frees
    with pytest.raises(ValueError, match="double free"):
        a.decref([b])
    with pytest.raises(ValueError, match="double free"):
        a.free([b])
    with pytest.raises(ValueError, match="unallocated"):
        a.incref([b])


def test_chain_hashes_prefix_property():
    toks = list(range(20))
    h8 = _chain_hashes(toks, 8)
    assert len(h8) == 2  # full blocks only
    # Chain keys: block i's key names the WHOLE prefix through block i.
    assert _chain_hashes(toks[:16], 8) == h8
    assert _chain_hashes(toks[:8] + [99] * 8, 8)[1] != h8[1]
    assert _chain_hashes([99] + toks[1:], 8)[0] != h8[0]
    # Seeded by block size: same tokens, different granularity, no alias.
    assert _chain_hashes(toks[:16], 4)[0] != h8[0]


def test_prefix_store_lru_and_refcount_pinned_eviction():
    a = BlockAllocator(8)
    store = PrefixStore()
    b1, b2, b3 = a.allocate(3)
    a.incref([b1, b2, b3])  # the store's references
    store.insert("k1", b1), store.insert("k2", b2), store.insert("k3", b3)
    a.decref([b1, b2, b3])  # the owning sequence finished
    assert store.lookup(["k1", "k2", "miss"]) == [b1, b2]
    a.incref([b1])  # a live sequence adopts k1: pinned against eviction
    freed = store.evict(a, need=2)
    # LRU order after the lookup refresh is k3, k1, k2 — k1 is pinned,
    # so k3 and k2 go.
    assert freed == 2 and "k1" in store and len(store) == 1
    a.decref([b1])
    assert store.flush(a) == 1
    assert a.num_free == a.num_allocatable


# ---------------------------------------------------------------- prefix --
def test_shared_prefix_parity_and_hit_rate(lm):
    """Shared-prefix batch through the prefix-caching engine must equal
    per-request generate(), with real cache hits and no block leaks."""
    rng = np.random.default_rng(0)
    prompts, news = _shared_prefix_requests(rng)
    want = _sequential_generate(lm, prompts, news)
    engine = Engine(lm, max_slots=2, block_size=4, max_len=64,
                    prefix_cache=True)
    got = engine.run([Request(p, m) for p, m in zip(prompts, news)])
    for i, (w, g) in enumerate(zip(want, got)):
        np.testing.assert_array_equal(w, g, err_msg=f"request {i}")
    rep = engine.last_run_telemetry["prefix_cache"]
    assert rep["hit_rate"] > 0 and rep["hit_blocks"] > 0
    assert rep["insertions"] > 0
    assert rep["kv_bytes_saved"] > 0
    # Every surviving allocator reference is the store's (slots drained):
    # anything else is a leak.
    alloc = engine.kv.allocator
    assert set(alloc._refs) == set(engine.kv.prefix.blocks)
    assert all(alloc.refcount(b) == 1 for b in engine.kv.prefix.blocks)


def test_cow_on_fully_cached_prompt(lm):
    """Re-serving an identical prompt finds its blocks fully cached; the
    admission cap (always recompute the last position) forces a write
    into a SHARED block, which must copy-on-write — bit-exact output,
    peers untouched."""
    rng = np.random.default_rng(1)
    prompt = rng.integers(0, 32, (12,)).astype(np.int32)  # 3 full blocks
    want = lm.generate(prompt[None], 6, temperature=0.0)[0]
    engine = Engine(lm, max_slots=2, block_size=4, max_len=64,
                    prefix_cache=True)
    first = engine.run([Request(prompt, 6)])
    second = engine.run([Request(prompt, 6)])
    np.testing.assert_array_equal(want, first[0])
    np.testing.assert_array_equal(want, second[0])
    assert engine.kv.cow_copies >= 1
    assert engine.last_run_telemetry["prefix_cache"]["hit_tokens"] > 0


# @slow (tier-1 budget): the decref-not-free invariant is unit-covered
# in-tier by the allocator/store tests above; this is the e2e drive.
@pytest.mark.slow
def test_preempt_shared_blocks_decrefs_not_frees(lm):
    """Preemption under pool pressure with shared prefixes: victims hold
    refcount>1 blocks, and release must DECREF them — afterwards the
    store's entries are intact and accounting balances to zero leaks."""
    rng = np.random.default_rng(2)
    prompts, news = _shared_prefix_requests(rng, shared_len=12, n=5,
                                            news=(6, 10))
    want = _sequential_generate(lm, prompts, news)
    # Starve the pool: enough for ~2.5 worst-case sequences.
    engine = Engine(lm, max_slots=3, block_size=4, max_len=64,
                    num_blocks=16, prefix_cache=True)
    got = engine.run([Request(p, m) for p, m in zip(prompts, news)])
    for i, (w, g) in enumerate(zip(want, got)):
        np.testing.assert_array_equal(w, g, err_msg=f"request {i}")
    assert engine.last_run_telemetry["preemptions"] > 0
    alloc = engine.kv.allocator
    assert set(alloc._refs) == set(engine.kv.prefix.blocks)


# @slow (tier-1 budget): refcount-aware LRU eviction is unit-covered
# in-tier above; this drives it under real allocation pressure.
@pytest.mark.slow
def test_store_eviction_under_distinct_prompt_pressure(lm):
    """Distinct prompts fill the store until allocation pressure forces
    refcount-aware LRU eviction; serving still completes exactly."""
    rng = np.random.default_rng(3)
    prompts = [rng.integers(0, 32, (12,)).astype(np.int32)
               for _ in range(6)]
    news = [4] * 6
    want = _sequential_generate(lm, prompts, news)
    engine = Engine(lm, max_slots=2, block_size=4, max_len=64,
                    num_blocks=13, prefix_cache=True)
    got = engine.run([Request(p, m) for p, m in zip(prompts, news)])
    for i, (w, g) in enumerate(zip(want, got)):
        np.testing.assert_array_equal(w, g, err_msg=f"request {i}")
    assert engine.kv.prefix.evictions > 0


# ------------------------------------------------------------------ int8 --
@pytest.mark.slow
def test_int8_kv_pools_shapes_ratio_and_fidelity(lm):
    """int8 KV pools store {q, scale} per block; the byte ratio over f32
    matches 4*hd/(hd+4) exactly, and greedy decode stays high-agreement
    with the f32 engine (fidelity-gated, NOT bit-exact — docs/PERF.md)."""
    rng = np.random.default_rng(4)
    prompts, news = _shared_prefix_requests(rng, shared_len=8, n=4)
    f32 = Engine(lm, max_slots=2, block_size=4, max_len=64)
    q8 = Engine(lm, max_slots=2, block_size=4, max_len=64,
                kv_dtype="int8")
    leaves = jax.tree_util.tree_leaves(
        q8.kv.caches,
        is_leaf=lambda x: isinstance(x, dict) and "q" in x)
    assert leaves and all(isinstance(l, dict) for l in leaves)
    assert all(l["q"].dtype == np.int8 and l["scale"].dtype == np.float32
               for l in leaves)
    hd = 16 // 2  # d_model / num_heads
    want_ratio = 4 * hd / (hd + 4)
    got_ratio = f32.kv.bytes_per_block() / q8.kv.bytes_per_block()
    assert got_ratio == pytest.approx(want_ratio)
    reqs = [Request(p, m) for p, m in zip(prompts, news)]
    a = f32.run(list(reqs))
    b = q8.run(list(reqs))
    agree = total = 0
    for x, y, p in zip(a, b, prompts):
        gx, gy = x[len(p):], y[len(p):]
        agree += int(np.sum(gx == gy))
        total += len(gx)
    assert agree / total >= 0.5, f"int8 KV agreement {agree}/{total}"


# ------------------------------------------------------------ speculative --
def test_spec_decode_token_exact_selfdraft(lm):
    """Draft == target: near-every proposal accepted, and the output is
    exactly generate()'s — the verify dispatch IS the decode step."""
    rng = np.random.default_rng(5)
    prompts, news = _shared_prefix_requests(rng, shared_len=8, n=4,
                                            news=(8, 12))
    want = _sequential_generate(lm, prompts, news)
    engine = Engine(lm, max_slots=2, block_size=4, max_len=64,
                    draft_model=lm, spec_k=3)
    got = engine.run([Request(p, m) for p, m in zip(prompts, news)])
    for i, (w, g) in enumerate(zip(want, got)):
        np.testing.assert_array_equal(w, g, err_msg=f"request {i}")
    spec = engine.last_run_telemetry["speculative"]
    assert spec["k"] == 3 and spec["rounds"] > 0
    assert spec["tokens_per_dispatch"] > 1.0  # a self-draft must win
    assert spec["accept_rate"] > 0.0


# @slow (tier-1 budget): greedy spec exactness stays in-tier via the
# self-draft test; this adds the disagreeing-draft (low-accept) angle.
@pytest.mark.slow
def test_spec_decode_token_exact_cold_draft(lm, draft_lm):
    """A barely-trained draft proposes garbage; acceptance collapses but
    the token stream must STILL be exactly generate()'s — rejection
    replays the target's own sampled token."""
    rng = np.random.default_rng(6)
    prompts, news = _shared_prefix_requests(rng, shared_len=8, n=3)
    want = _sequential_generate(lm, prompts, news)
    engine = Engine(lm, max_slots=2, block_size=4, max_len=64,
                    draft_model=draft_lm, spec_k=3)
    got = engine.run([Request(p, m) for p, m in zip(prompts, news)])
    for i, (w, g) in enumerate(zip(want, got)):
        np.testing.assert_array_equal(w, g, err_msg=f"request {i}")


@pytest.mark.slow
def test_spec_decode_sampled_bit_exact(lm, draft_lm):
    """Sampled serving: the verify path reuses the engine's per-token
    key derivation, so speculative output is bit-identical to the
    vanilla engine's for pinned request seeds."""
    rng = np.random.default_rng(7)
    prompts, news = _shared_prefix_requests(rng, shared_len=8, n=3)
    reqs = lambda: [Request(p, m, seed=100 + i)
                    for i, (p, m) in enumerate(zip(prompts, news))]
    vanilla = Engine(lm, max_slots=2, block_size=4, max_len=64,
                     temperature=1.0, top_k=8)
    spec = Engine(lm, max_slots=2, block_size=4, max_len=64,
                  temperature=1.0, top_k=8, draft_model=draft_lm,
                  spec_k=3)
    a = vanilla.run(reqs())
    b = spec.run(reqs())
    for i, (w, g) in enumerate(zip(a, b)):
        np.testing.assert_array_equal(w, g, err_msg=f"request {i}")


@pytest.mark.slow
def test_spec_decode_exact_across_preemption(lm):
    """Pool pressure preempts mid-spec; requeued sequences re-prefill
    and keep speculating — still exactly generate()."""
    rng = np.random.default_rng(8)
    prompts, news = _shared_prefix_requests(rng, shared_len=12, n=5,
                                            news=(6, 10))
    want = _sequential_generate(lm, prompts, news)
    engine = Engine(lm, max_slots=3, block_size=4, max_len=64,
                    num_blocks=14, draft_model=lm, spec_k=3)
    got = engine.run([Request(p, m) for p, m in zip(prompts, news)])
    for i, (w, g) in enumerate(zip(want, got)):
        np.testing.assert_array_equal(w, g, err_msg=f"request {i}")
    assert engine.last_run_telemetry["preemptions"] > 0


@pytest.mark.slow
def test_spec_update_weights_flushes_prefix_and_stays_exact(lm):
    """Weight hot-swap between runs: the prefix store is FLUSHED (cached
    KV under old weights must not seed new requests), and the
    speculative engine's post-swap output equals post-swap generate()
    even though the draft still runs the old weights (stale drafts only
    lower acceptance, never change tokens)."""
    rng = np.random.default_rng(9)
    prompts, news = _shared_prefix_requests(rng, shared_len=8, n=3)
    engine = Engine(lm, max_slots=2, block_size=4, max_len=64,
                    prefix_cache=True, draft_model=lm, spec_k=3)
    engine.run([Request(p, m) for p, m in zip(prompts, news)])
    assert len(engine.kv.prefix) > 0
    new_params = jax.tree_util.tree_map(lambda x: x * 1.05, lm.params)
    old_params = lm.params
    engine.update_weights(new_params)
    assert len(engine.kv.prefix) == 0  # staleness contract
    try:
        lm.params = new_params
        want = _sequential_generate(lm, prompts, news)
        got = engine.run([Request(p, m) for p, m in zip(prompts, news)])
        for i, (w, g) in enumerate(zip(want, got)):
            np.testing.assert_array_equal(w, g, err_msg=f"request {i}")
    finally:
        lm.params = old_params
        engine.update_weights(old_params)


@pytest.mark.slow
def test_fixed_shape_dispatches_never_recompile(lm, draft_lm):
    """Batch churn — different tails, hit patterns, acceptance runs —
    must ride the warm fixed-shape programs: decode, verify and draft
    decode compile exactly once. (Prefill is excluded: its bucketed
    shape legitimately varies with the cached-prefix offset.)"""
    rng = np.random.default_rng(11)
    p1, n1 = _shared_prefix_requests(rng, shared_len=8, n=3)
    p2, n2 = _shared_prefix_requests(rng, shared_len=12, n=4)
    engine = Engine(lm, max_slots=2, block_size=4, max_len=64,
                    prefix_cache=True)
    engine.run([Request(p, m) for p, m in zip(p1, n1)])  # warm
    with assert_no_recompile(engine._decode_jit):
        engine.run([Request(p, m) for p, m in zip(p2, n2)])
    spec = Engine(lm, max_slots=2, block_size=4, max_len=64,
                  prefix_cache=True, draft_model=draft_lm, spec_k=3)
    spec.run([Request(p, m) for p, m in zip(p1, n1)])  # warm
    with assert_no_recompile(spec._verify_jit, spec._draft_decode_jit):
        spec.run([Request(p, m) for p, m in zip(p2, n2)])


def test_spec_headroom_request_validation(lm):
    engine = Engine(lm, max_slots=1, block_size=4, max_len=16,
                    draft_model=lm, spec_k=4)
    with pytest.raises(ValueError, match="speculative headroom"):
        engine.run([Request(np.arange(8, dtype=np.int32), 8)])


def test_spec_k_validation(lm):
    with pytest.raises(ValueError, match="spec_k"):
        Engine(lm, max_slots=1, block_size=4, max_len=32,
               draft_model=lm, spec_k=1)


# ----------------------------------------------------------------- fleet --
def test_fleet_suffix_only_handoff(lm):
    """Prefix-caching fleet: the router places by prefix affinity and
    payloads ship ONLY the non-cached suffix — fewer bytes than full
    handoffs, token streams unchanged."""
    from distributed_tpu.fleet import ServingFleet

    rng = np.random.default_rng(10)
    prompts, news = _shared_prefix_requests(rng, shared_len=16, n=5)
    want = _sequential_generate(lm, prompts, news)
    fleet = ServingFleet(lm, decode_replicas=2, prefill_replicas=1,
                         max_slots=4, block_size=4, max_len=64,
                         prefix_cache=True)
    outs = fleet.run([Request(p, m) for p, m in zip(prompts, news)])
    for i, (w, g) in enumerate(zip(want, outs)):
        np.testing.assert_array_equal(w, g, err_msg=f"request {i}")
    h = fleet.last_run_telemetry["handoffs"]
    assert h["suffix_trims"] > 0
    assert 0 < h["bytes_shipped"] < h["bytes_full"]
    assert h["bytes_saved"] == h["bytes_full"] - h["bytes_shipped"]
    assert h["trim_stale"] == 0


def test_trim_kv_unit(lm):
    """trim_kv drops exactly the leading store-hit blocks and re-keys
    the runs; an empty/missing store is a no-op."""
    from distributed_tpu.fleet.handoff import pack_kv, trim_kv

    kv = PagedKVCache(lm.module, lm.params, max_slots=1, block_size=4,
                      max_blocks_per_seq=8, num_blocks=9,
                      dtype=np.float32)
    assert kv.reserve(0, 12)  # 3 blocks
    toks = list(range(12))
    payload = pack_kv(kv, 0, 12, tokens=toks)
    assert len(payload.prefix_hashes) == 3
    same, skipped = trim_kv(payload, None)
    assert skipped == 0 and same is payload
    store = PrefixStore()
    alloc = BlockAllocator(4)
    (b,) = alloc.allocate(1)
    store.insert(payload.prefix_hashes[0], b)
    trimmed, skipped = trim_kv(payload, store)
    assert skipped == 1 and trimmed.skip_blocks == 1
    for key, data in trimmed.blocks.items():
        assert key.split("@")[-2].startswith("1,") and data.shape[0] == 2
    # Non-contiguous hit (block 2 cached, block 1 not): the walk stops
    # at the first miss, so nothing past block 0 is dropped.
    store2 = PrefixStore()
    store2.insert(payload.prefix_hashes[2], b)
    _, skipped2 = trim_kv(payload, store2)
    assert skipped2 == 0
