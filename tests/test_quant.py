"""Int8 weight-only quantization (distributed_tpu.quant).

Pins: the per-channel symmetric scheme itself (bounded per-element error,
scale shapes, double-quantize guard), the serving surfaces from quantized
weights (predict / greedy generate / serving.Engine token parity), the
checkpoint round-trips the ISSUE names (f32 ckpt -> quantize-on-load, and
quantized q+scale trees through Checkpointer AND ShardedCheckpointer),
and the int8 collective accounting in Strategy.comm_bytes_estimate.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import distributed_tpu as dtpu
from distributed_tpu import quant

VOCAB, LAYERS, D, HEADS, MAXLEN = 96, 2, 32, 2, 64


def _lm():
    m = dtpu.Model(dtpu.models.transformer_lm(
        VOCAB, num_layers=LAYERS, d_model=D, num_heads=HEADS,
        max_len=MAXLEN))
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    m.build((16,), seed=0)
    return m


def _lm_wide():
    # Build-only (no step ever traces): wide enough that the f32-kept 1-D
    # leaves and the per-channel scales are the ~1% dilution they are on
    # real serving shapes — the byte/collective gates are meaningless on
    # d=32 toys where biases are 5% of the tree.
    m = dtpu.Model(dtpu.models.transformer_lm(
        VOCAB, num_layers=2, d_model=128, num_heads=4, max_len=MAXLEN))
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    m.build((16,), seed=0)
    return m


def _toks(b=4, t=16, seed=0):
    return np.random.default_rng(seed).integers(
        0, VOCAB, (b, t)).astype(np.int32)


# ------------------------------------------------------------ the scheme --
def test_quantize_leaf_roundtrip_error_bound():
    w = np.asarray(jax.random.normal(jax.random.PRNGKey(0), (64, 24)))
    qd = quant.quantize_leaf(w)
    assert qd["q"].dtype == jnp.int8 and qd["q"].shape == w.shape
    assert qd["scale"].dtype == jnp.float32 and qd["scale"].shape == (24,)
    back = np.asarray(quant.dequantize(qd))
    # Symmetric round-to-nearest: error <= scale/2 per element.
    assert np.all(np.abs(back - w) <= np.asarray(qd["scale"]) / 2 + 1e-7)


def test_quantize_tree_selects_matrices_only():
    tree = {"kernel": jnp.ones((8, 4)), "bias": jnp.ones((4,)),
            "step": jnp.arange(3)}
    qt = quant.quantize_tree(tree)
    assert quant.is_quantized_leaf(qt["kernel"])
    assert not quant.is_quantized_leaf(qt["bias"])
    assert qt["bias"].dtype == jnp.float32
    assert qt["step"].dtype == tree["step"].dtype
    with pytest.raises(ValueError, match="already"):
        quant.quantize_tree(qt)


def test_zero_channel_scale_is_finite():
    w = np.zeros((8, 4), np.float32)
    w[:, 0] = 3.0
    qd = quant.quantize_leaf(w)
    assert np.all(np.isfinite(np.asarray(qd["scale"])))
    assert np.array_equal(np.asarray(quant.dequantize(qd)), w)


# -------------------------------------------------------- serving parity --
def test_predict_logits_bounded_and_top1():
    m = _lm()
    q = _lm()
    quant.quantize_model(q)
    x = _toks()
    ref = m.predict(x, batch_size=4)
    out = q.predict(x, batch_size=4)
    assert float(np.max(np.abs(out - ref))) < 0.25  # bounded logit error
    agree = float(np.mean(np.argmax(out, -1) == np.argmax(ref, -1)))
    assert agree >= 0.9  # top-1 agreement, teacher-forced


def test_greedy_generate_agreement():
    m = _lm()
    q = _lm()
    quant.quantize_model(q)
    x = _toks(b=2, t=8)
    g_ref = m.generate(x, 8, temperature=0.0)
    g_q = q.generate(x, 8, temperature=0.0)
    assert g_ref.shape == g_q.shape
    # Greedy decode re-feeds its own tokens, so one flipped near-tie can
    # fork the suffix — pin a high agreement fraction, not equality.
    assert float(np.mean(g_ref == g_q)) >= 0.8


def test_engine_serves_quantized_weights_token_exact():
    """Continuous-batching serving from int8 weights is token-identical
    to the quantized model's own generate() — the engine contract from
    test_serving, now over a quantized param tree."""
    import distributed_tpu.serving as serving

    q = _lm()
    quant.quantize_model(q)
    x = _toks(b=3, t=8, seed=2)
    engine = serving.Engine(q, max_slots=2, block_size=8, max_len=32)
    outs = engine.run([(x[i], 6) for i in range(3)])
    for i in range(3):
        ref = q.generate(x[i:i + 1], 6, temperature=0.0)[0]
        assert np.array_equal(outs[i], ref)


def test_fit_raises_on_quantized_model():
    q = _lm()
    quant.quantize_model(q)
    x = _toks()
    with pytest.raises(RuntimeError, match="quantized"):
        q.fit(x, x, batch_size=4, epochs=1, verbose=0)
    with pytest.raises(ValueError, match="already"):
        quant.quantize_model(q)


# --------------------------------------------------------- checkpointing --
def test_quantize_on_load_from_f32_checkpoint(tmp_path):
    """The serving flow: f32 training checkpoint -> restore -> quantize.
    Equals quantizing the original weights directly (quantization is a
    pure function of the f32 values)."""
    m = _lm()
    ckpt = dtpu.Checkpointer(tmp_path / "ck")
    ckpt.save(m, step=0)

    fresh = _lm()
    fresh.build((16,), seed=1)  # different init: restore must overwrite
    ckpt.restore_into(fresh)
    quant.quantize_model(fresh)

    direct = _lm()
    quant.quantize_model(direct)
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(fresh.params)),
                    jax.tree_util.tree_leaves(jax.device_get(direct.params))):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_quantized_checkpoint_roundtrip(tmp_path):
    """Quantized q + scale trees round-trip EXACTLY through Checkpointer
    (int8 payloads and f32 scales are both lossless in npz)."""
    q = _lm()
    quant.quantize_model(q)
    ckpt = dtpu.Checkpointer(tmp_path / "ck")
    ckpt.save(q, step=7)

    q2 = _lm()
    quant.quantize_model(q2)  # same weights -> same structure
    step = ckpt.restore_into(q2)
    assert step == 7
    assert quant.is_quantized(q2.params)
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(q.params)),
                    jax.tree_util.tree_leaves(jax.device_get(q2.params))):
        assert np.array_equal(np.asarray(a), np.asarray(b))
        assert a.dtype == b.dtype


def test_quantized_sharded_checkpoint_roundtrip(tmp_path):
    """Same exact round-trip through ShardedCheckpointer under FSDP: the
    int8 q leaves save/restore as per-process shard blocks."""
    strat = dtpu.FSDP()
    with strat.scope():
        q = _lm()
    quant.quantize_model(q)
    ckpt = dtpu.ShardedCheckpointer(tmp_path / "sck")
    ckpt.save(q, step=3)

    with strat.scope():
        q2 = _lm()
    quant.quantize_model(q2)
    assert ckpt.restore_into(q2) == 3
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(q.params)),
                    jax.tree_util.tree_leaves(jax.device_get(q2.params))):
        assert np.array_equal(np.asarray(a), np.asarray(b))
        assert a.dtype == b.dtype


# ------------------------------------------------- bytes and collectives --
def test_param_bytes_ratio():
    m = _lm_wide()
    host = jax.device_get(m.params)
    ratio = (quant.tree_param_bytes(host)
             / quant.tree_param_bytes(quant.quantize_tree(host)))
    # biases/norms/scales stay f32, so the ratio sits under the ideal 4x
    # but must clear the serving gate on even this small LM.
    assert ratio >= 3.5


def test_fsdp_comm_bytes_int8(devices):
    strat = dtpu.FSDP()
    host = jax.device_get(_lm_wide().params)
    qtree = quant.quantize_tree(host)
    gk = "gathered_param_bytes_per_device"
    f32 = strat.comm_bytes_estimate(host)[gk]
    bf16 = strat.comm_bytes_estimate(host, compute_dtype=jnp.bfloat16)[gk]
    int8 = strat.comm_bytes_estimate(qtree, compute_dtype=jnp.bfloat16)[gk]
    assert f32 / int8 >= 3.5  # 4x on weights, diluted ~1% by f32 leaves
    assert bf16 / int8 >= 1.9  # 2x on weights (exact), same dilution
    # the q payloads themselves are priced at exactly 1 byte/elem
    one_kernel = {"k": host["dense"]["kernel"]}
    q_kernel = quant.quantize_tree(one_kernel)
    b_q = strat.comm_bytes_estimate(
        {"k": {"q": q_kernel["k"]["q"]}}, compute_dtype=jnp.bfloat16)[gk]
    b_bf16 = strat.comm_bytes_estimate(
        one_kernel, compute_dtype=jnp.bfloat16)[gk]
    assert b_bf16 == 2 * b_q


def test_quantized_model_under_fsdp_serves(devices):
    """Quantized weights place under FSDP (int8 shards + f32 scales) and
    the decode path still matches the single-device quantized model."""
    strat = dtpu.FSDP()
    with strat.scope():
        q = _lm_wide()
    quant.quantize_model(q)
    # q leaves actually sharded int8 on the mesh
    leaf = q.params["residual"]["main"]["multi_head_attention"]["wq"]
    assert leaf["q"].dtype == jnp.int8
    assert len({s.device for s in leaf["q"].addressable_shards}) == 8

    ref = _lm_wide()
    quant.quantize_model(ref)
    x = _toks(b=8, t=8, seed=5)
    np.testing.assert_allclose(
        q.predict(x, batch_size=8), ref.predict(x, batch_size=8),
        rtol=2e-5, atol=2e-6,
    )


def test_mixed_precision_policy_composes():
    """Quantized weights under compile(precision="mixed_bfloat16"): the
    dequantized kernels cast to bf16 compute, logits stay close to the
    f32-compute quantized model."""
    q32 = _lm()
    quant.quantize_model(q32)
    qbf = dtpu.Model(dtpu.models.transformer_lm(
        VOCAB, num_layers=LAYERS, d_model=D, num_heads=HEADS,
        max_len=MAXLEN))
    qbf.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                precision="mixed_bfloat16")
    qbf.build((16,), seed=0)
    quant.quantize_model(qbf)
    assert qbf.decode_dtype() == jnp.bfloat16
    x = _toks(b=2, t=8, seed=7)
    a = q32.predict(x, batch_size=2)
    b = qbf.predict(x, batch_size=2)
    assert float(np.max(np.abs(a - b))) < 0.5  # bf16 rounding, not garbage
    agree = float(np.mean(np.argmax(a, -1) == np.argmax(b, -1)))
    assert agree >= 0.9
