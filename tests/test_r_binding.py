"""R entrypoint: structural checks always; Rscript end-to-end when available.

The R binding is a hard parity requirement (BASELINE.json north star: "MNIST
CNN >=98% ... from the R entrypoint"; reference R trainer README.md:118-154).
This environment has no R installed, so the e2e path is gated; the structural
tests pin the R<->Python API contract so drift breaks CI here.
"""

import re
import shutil
import subprocess
from pathlib import Path

import pytest

R_DIR = Path(__file__).resolve().parents[1] / "r"
PKG = R_DIR / "distributedtpu"


def _r_sources():
    return sorted((PKG / "R").glob("*.R"))


class TestStructure:
    def test_package_layout(self):
        assert (PKG / "DESCRIPTION").is_file()
        assert (PKG / "NAMESPACE").is_file()
        assert _r_sources(), "no R sources"

    @pytest.mark.smoke
    def test_exports_are_defined(self):
        # Every export(<name>) in NAMESPACE has a definition in R/ sources.
        ns = (PKG / "NAMESPACE").read_text()
        exports = re.findall(r"^export\(([^)]+)\)$", ns, re.M)
        src = "\n".join(p.read_text() for p in _r_sources())
        for name in exports:
            name = name.strip('"`')
            if name == "%>%":
                pat = r"`%>%`\s*<-"
            else:
                pat = rf"^{re.escape(name)}(\.[A-Za-z_.]+)?\s*<-\s*function"
            assert re.search(pat, src, re.M), f"export {name} has no definition"

    def test_python_api_contract(self):
        """Every dtpu()$<attr> chain the R code calls must exist in the
        Python package — this is the binding's real interface test."""
        import distributed_tpu as dtpu_mod

        src = "\n".join(p.read_text() for p in _r_sources())
        chains = set(re.findall(r"dtpu\(\)\$([A-Za-z_][A-Za-z_$0-9]*)", src))
        for chain in chains:
            obj = dtpu_mod
            for attr in chain.split("$"):
                attr = attr.strip("`")
                assert hasattr(obj, attr), (
                    f"R calls dtpu()${chain} but Python lacks .{attr}"
                )
                obj = getattr(obj, attr)

    def test_examples_mirror_reference_flow(self):
        dist = (R_DIR / "examples" / "distributed.R").read_text()
        # The reference's contract pieces must all appear:
        for needle in [
            "set_cluster_spec",
            "multi_worker_mirrored_strategy",
            "with_strategy_scope",
            "batch_size * num_workers",
            "save_model_hdf5",
        ]:
            assert needle in dist, f"distributed.R missing {needle}"


@pytest.mark.skipif(shutil.which("Rscript") is None, reason="R not installed")
class TestRscript:
    def test_end_to_end_local_train(self, tmp_path):
        script = tmp_path / "smoke.R"
        script.write_text(
            f"""
            for (f in list.files("{PKG}/R", full.names = TRUE)) source(f)
            .globals$dtpu <- reticulate::import("distributed_tpu")
            print(dtpu_version())
            m <- dtpu_model(mnist_cnn(10L))
            m %>% compile(optimizer = "sgd", learning_rate = 0.05,
                          loss = "sparse_categorical_crossentropy",
                          metrics = c("accuracy"))
            d <- dataset_mnist()
            h <- m %>% fit(d$train$x, d$train$y, batch_size = 64L,
                           epochs = 1L, steps_per_epoch = 5L, verbose = 0L)
            stopifnot(length(h$metrics$loss) == 1)
            cat("R_E2E_OK\\n")
            """
        )
        out = subprocess.run(
            ["Rscript", str(script)], capture_output=True, text=True,
            timeout=600,
        )
        assert "R_E2E_OK" in out.stdout, out.stderr
