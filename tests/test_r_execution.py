"""EXECUTE the R sources in CI (VERDICT r4 missing #2 / next-step #3).

tests/r_lang.py parses every file under r/ with a real R parser (body-level
syntax errors fail here, not just formals drift), and tests/r_interp.py
evaluates them with R semantics — lazy promises, S3 dispatch, the package's
own `%>%` body, tryCatch — against the REAL Python package through the
reticulate marshaling rules of tests/reticulate_sim.py.

Covered end-to-end:
- r/examples/local.R       (the reference's R entrypoint, README.md:45-76)
- r/examples/distributed.R (cluster spec + scope + global batch + export)
- r/examples/spark_barrier.R (sparklyr mocked; closures run per partition,
  rank-0 model returns base64 through the result column, README.md:170-247)
- model.R save/load including BatchNorm running stats (VERDICT r4 weak #5)
- injected-typo detection: a syntax error OR a body-level runtime typo in
  any R source fails these tests.
"""

import os
import re
import shutil
from pathlib import Path

import numpy as np
import pytest

import r_interp
import r_lang
from r_interp import RError, RList, make_interp, r_class, _scalar
from reticulate_sim import RVector, r_character, r_int

REPO = Path(__file__).resolve().parent.parent
R_PKG = REPO / "r" / "distributedtpu" / "R"
R_EXAMPLES = REPO / "r" / "examples"
ALL_R_FILES = sorted(R_PKG.glob("*.R")) + sorted(R_EXAMPLES.glob("*.R"))


# ------------------------------------------------------------------ parse --
@pytest.mark.smoke
def test_every_r_source_parses():
    assert len(ALL_R_FILES) == 7, ALL_R_FILES
    for path in ALL_R_FILES:
        stmts = r_lang.parse_file(path)  # raises RParseError on any typo
        assert stmts, f"{path} parsed to an empty program"


@pytest.mark.smoke
def test_injected_syntax_error_is_caught(tmp_path):
    """A typo INSIDE a function body (unbalanced paren deep in fit's
    body) must fail the parse — the exact blind spot formals-level
    validation had."""
    src = (R_PKG / "model.R").read_text()
    broken = src.replace("batch_size = as.integer(batch_size),",
                         "batch_size = as.integer(batch_size,", 1)
    assert broken != src
    with pytest.raises(r_lang.RParseError):
        r_lang.parse(broken, "model.R")


# @slow (tier-1 budget, PR 16): ~8s full local.R run to hit the typo;
# the parse-time error path stays in-tier above, and the runtime R
# execution path stays in-tier via test_local_example_executes_and_trains.
@pytest.mark.slow
def test_injected_body_typo_fails_at_runtime(tmp_path):
    """A *syntactically valid* typo inside an R body (misspelled callee)
    parses fine but must fail when the body executes."""
    rdir = tmp_path / "R"
    shutil.copytree(R_PKG, rdir)
    src = (rdir / "model.R").read_text()
    broken = src.replace("as.integer(batch_size)", "as.intger(batch_size)", 1)
    assert broken != src
    (rdir / "model.R").write_text(broken)
    interp = r_interp.Interp(r_dir=str(rdir))
    with pytest.raises(RError, match="as.intger"):
        interp.run_file(R_EXAMPLES / "local.R")


# -------------------------------------------------------------- execution --
def test_local_example_executes_and_trains():
    """r/examples/local.R — the reference's R entrypoint flow
    (README.md:45-76) — runs for real: library() loads the package
    sources, %>% executes its own package.R body, compile/fit dispatch via
    S3, and the model genuinely trains on the Python side."""
    interp = make_interp()
    interp.run_file(R_EXAMPLES / "local.R")
    model = interp.global_env.lookup("model")
    assert "dtpu_model" in r_class(model).values
    # The Python Model underneath really trained: 3 epochs x 5 steps.
    py_model = model.value._obj
    assert py_model.step == 15
    # And the R-visible epoch count from `epochs <- 3L` drove it.
    assert _scalar(interp.global_env.lookup("epochs")) == 3


# @slow (tier-1 budget, PR 17): ~10s full local.R run; the R runtime
# execution path stays in-tier via test_local_example_executes_and_trains
# and result marshalling via test_evaluate_and_weight_roundtrip_from_r.
@pytest.mark.slow
def test_local_example_history_marshals_back():
    """fit's return value crosses back into R as a dtpu_history whose
    metrics are R double vectors (model.R:76-78); print.dtpu_history's
    body (cat/paste/signif) executes."""
    interp = make_interp()
    interp.run_source("""
    library(distributedtpu)
    mnist <- dataset_mnist()
    model <- dtpu_model(mnist_cnn(10L))
    model %>% compile(optimizer = "sgd", learning_rate = 0.01,
                      loss = "sparse_categorical_crossentropy",
                      metrics = c("accuracy"))
    hist <- model %>% fit(mnist$train$x, mnist$train$y,
                          batch_size = 64L, epochs = 2L,
                          steps_per_epoch = 3L, verbose = 0L)
    print(hist)
    acc <- hist$metrics$accuracy
    """)
    acc = interp.global_env.lookup("acc")
    assert isinstance(acc, RVector) and acc.kind == "double"
    assert len(acc) == 2  # one entry per epoch
    printed = "".join(interp.output)
    assert "loss" in printed and "accuracy" in printed


# @slow (tier-1 budget, PR 17): ~12s full local.R run; the R runtime
# execution path stays in-tier via test_local_example_executes_and_trains
# and R-side persistence via the reticulate weights-roundtrip test.
@pytest.mark.slow
def test_evaluate_and_weight_roundtrip_from_r(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    interp = make_interp()
    interp.run_source("""
    library(distributedtpu)
    mnist <- dataset_mnist()
    model <- dtpu_model(mnist_cnn(10L))
    model %>% compile(optimizer = "sgd", learning_rate = 0.05,
                      loss = "sparse_categorical_crossentropy",
                      metrics = c("accuracy"))
    model %>% fit(mnist$train$x, mnist$train$y, batch_size = 64L,
                  epochs = 1L, steps_per_epoch = 5L, verbose = 0L)
    ev <- evaluate(model, mnist$test$x, mnist$test$y, batch_size = 256L)
    save_model_weights_hdf5(model, "w.h5")
    m2 <- dtpu_model(mnist_cnn(10L))
    m2 %>% compile(optimizer = "sgd", learning_rate = 0.05,
                   loss = "sparse_categorical_crossentropy",
                   metrics = c("accuracy"))
    m2$build(c(28L, 28L, 1L))
    load_model_weights_hdf5(m2, "w.h5")
    ev2 <- evaluate(m2, mnist$test$x, mnist$test$y, batch_size = 256L)
    """)
    ev = interp.global_env.lookup("ev")
    ev2 = interp.global_env.lookup("ev2")
    assert isinstance(ev, RList) and ev.names is not None
    for name in ev.names:
        assert _scalar(ev.get(name)) == pytest.approx(
            _scalar(ev2.get(name))), name


@pytest.mark.slow
def test_save_model_hdf5_preserves_batchnorm_stats(tmp_path, monkeypatch):
    """VERDICT r4 weak #5: the keras-named save_model_hdf5 dropped model
    STATE (BatchNorm running stats), so a reloaded resnet inferred with
    reset statistics. Now it must round-trip them: predictions of the
    reloaded model match the trained one exactly."""
    monkeypatch.chdir(tmp_path)
    interp = make_interp()
    interp.run_source("""
    library(distributedtpu)
    model <- dtpu_model(resnet50(num_classes = 10L, small_inputs = TRUE))
    model %>% compile(optimizer = "sgd", learning_rate = 0.05,
                      loss = "sparse_categorical_crossentropy")
    """)
    # Tiny real arrays from the Python side (8x8 keeps the CPU-sim convs
    # fast; what matters is that training moves the BN running stats).
    import distributed_tpu as dtpu

    rng = np.random.default_rng(0)
    x = rng.standard_normal((16, 8, 8, 3)).astype(np.float64)
    y = rng.integers(0, 10, (16,)).astype(np.int32)
    from reticulate_sim import RArray

    interp.global_env.define("x", RArray(x, "double"))
    interp.global_env.define("y", RArray(y.astype(np.int32), "integer"))
    interp.run_source("""
    model %>% fit(x, y, batch_size = 16L, epochs = 1L,
                  steps_per_epoch = 2L, verbose = 0L)
    p1 <- predict_on_batch(model, x, batch_size = 16L)
    save_model_hdf5(model, "full.h5")
    m2 <- dtpu_model(resnet50(num_classes = 10L, small_inputs = TRUE))
    m2 %>% compile(optimizer = "sgd", learning_rate = 0.05,
                   loss = "sparse_categorical_crossentropy")
    m2$build(c(8L, 8L, 3L))
    load_model_hdf5(m2, "full.h5")
    p2 <- predict_on_batch(m2, x, batch_size = 16L)
    """)
    p1 = interp.global_env.lookup("p1").array
    p2 = interp.global_env.lookup("p2").array
    # Bit-identical inference => params AND BatchNorm stats round-tripped.
    np.testing.assert_array_equal(p1, p2)
    # Sanity: the trained stats actually differ from a fresh model's
    # (otherwise this test would pass vacuously).
    m3 = interp.run_source("""
    m3 <- dtpu_model(resnet50(num_classes = 10L, small_inputs = TRUE))
    m3 %>% compile(optimizer = "sgd", learning_rate = 0.05,
                   loss = "sparse_categorical_crossentropy")
    m3$build(c(8L, 8L, 3L))
    p3 <- predict_on_batch(m3, x, batch_size = 16L)
    """)
    p3 = interp.global_env.lookup("p3").array
    assert not np.array_equal(p1, p3)


@pytest.mark.slow
def test_distributed_example_executes(tmp_path, monkeypatch):
    """r/examples/distributed.R: cluster spec lands in $DTPU_CONFIG with
    the reference's worker-list schema (README.md:84-89), construction
    happens inside the strategy scope, the global batch is
    batch_size * num_workers, and the trained model exports HDF5."""
    monkeypatch.chdir(tmp_path)
    monkeypatch.delenv("DTPU_CONFIG", raising=False)
    interp = make_interp()
    interp.run_file(R_EXAMPLES / "distributed.R")
    import json

    spec = json.loads(os.environ["DTPU_CONFIG"])
    assert len(spec["cluster"]["worker"]) == 4
    assert spec["task"] == {"type": "worker", "index": 0}
    model = interp.global_env.lookup("model")
    assert "dtpu_model" in r_class(model).values
    assert model.value._obj.step == 15
    assert (tmp_path / "trained.hdf5").exists()
    monkeypatch.delenv("DTPU_CONFIG", raising=False)


@pytest.mark.slow
def test_spark_barrier_example_executes(tmp_path, monkeypatch):
    """r/examples/spark_barrier.R end to end with sparklyr mocked at the
    API boundary: the barrier closure runs once per partition (rank +
    peer list injected like README.md:180-183), rank 0's trained model
    comes back base64-encoded in the result column, and the driver
    decodes it to model.hdf5 (README.md:236-247)."""
    monkeypatch.chdir(tmp_path)
    monkeypatch.delenv("DTPU_CONFIG", raising=False)
    interp = make_interp()
    # In real R the pipe comes in via sparklyr's magrittr re-export; here
    # the distributedtpu package provides the (behaviorally identical)
    # fallback pipe, so load it before the driver script runs.
    interp.run_source("library(distributedtpu)")

    addresses = r_character(
        "10.1.0.1:45001", "10.1.0.2:45002", "10.1.0.3:45003")
    closure_runs = []

    def spark_config():
        return r_interp.REnv()

    def spark_connect(**kw):
        assert _scalar(kw["master"]) == "yarn"
        return r_character("sc-token")

    def sdf_len(sc, n, **kw):
        return r_int(int(_scalar(n)))

    def spark_apply(sdf, f, **kw):
        assert _scalar(kw["barrier"]) is True
        n = int(_scalar(sdf))
        rows = []
        for p in range(n):
            barrier = RList([addresses, r_int(p)], ["address", "partition"])
            out = interp.call_function(
                f,
                [(None, interp.value_promise(RList([]))),
                 (None, interp.value_promise(barrier))],
                interp.global_env,
            )
            closure_runs.append(p)
            rows.append(_scalar(out))
        return RList([r_character(*rows)], ["address"])

    def collect(x):
        return x

    interp.register_package("sparklyr", {
        "spark_config": spark_config,
        "spark_connect": spark_connect,
        "sdf_len": sdf_len,
        "spark_apply": spark_apply,
        "collect": collect,
    })
    interp.run_file(R_EXAMPLES / "spark_barrier.R")

    assert closure_runs == [0, 1, 2]
    result = interp.global_env.lookup("result")
    rows = result.get("address").values
    assert len(rows) == 3
    # Rank 0 returned base64 (long); ranks 1-2 returned accuracy strings.
    assert len(rows[0]) > 1000
    for acc_str in rows[1:]:
        assert 0.0 <= float(acc_str) <= 1.0, acc_str
    # The driver decoded rank 0's model and it is a readable HDF5/weights
    # file the Python side can import.
    assert (tmp_path / "model.hdf5").exists()
    import distributed_tpu as dtpu

    tree, _ = dtpu.checkpoint.import_hdf5(str(tmp_path / "model.hdf5"))
    assert "params" in tree  # save_model_hdf5 writes params AND state
    monkeypatch.delenv("DTPU_CONFIG", raising=False)


# @slow (tier-1 budget, PR 10): 12s sweep; representative exports
# still execute in-tier via the other r_execution tests.
@pytest.mark.slow
def test_every_small_r_export_executes(tmp_path, monkeypatch):
    """Sweep the exported wrappers the examples don't touch, so EVERY
    exported R function's body has executed in CI (the examples cover the
    training flow; this covers the rest). TensorBoard is exercised for
    its construction path (TF import happens chief-side at train begin)."""
    monkeypatch.chdir(tmp_path)
    interp = make_interp()
    interp.run_source("""
    library(distributedtpu)
    v <- dtpu_version()
    install_distributed_tpu()           # reticulate::py_install is stubbed
    s1 <- single_device_strategy()
    s2 <- data_parallel_strategy()
    n <- num_replicas_in_sync(s2)
    cnn <- cifar_cnn(10L)
    fm <- dataset_fashion_mnist()
    cf <- dataset_cifar10(normalize = FALSE)
    m <- dtpu_model(cifar_cnn(10L))
    m %>% compile(optimizer = "sgd", learning_rate = 0.01,
                  loss = "sparse_categorical_crossentropy")
    m$build(c(32L, 32L, 3L))
    summary_model(m)
    cb1 <- model_checkpoint_callback("ckpts", save_freq = "epoch",
                                     keep = 2L, restore = FALSE)
    cb2 <- early_stopping_callback(monitor = "loss", patience = 2L)
    cb3 <- reduce_lr_on_plateau_callback(factor = 0.5, patience = 1L)
    cb4 <- tensorboard_callback("tb")
    """)
    assert isinstance(_scalar(interp.global_env.lookup("v")), str)
    assert _scalar(interp.global_env.lookup("n")) == 8  # 8-device sim
    fm = interp.global_env.lookup("fm")
    assert fm.names == ["train", "test"]
    cf = interp.global_env.lookup("cf")
    x = cf.get("train").get("x")
    # normalize=FALSE marshals back as an INTEGER array (uint8 -> int32)
    from reticulate_sim import RArray

    assert isinstance(x, RArray) and x.kind == "integer"
    for name in ("cb1", "cb2", "cb3", "cb4"):
        cb = interp.global_env.lookup(name)
        assert cb.__class__.__name__ == "RProxy", name


# ------------------------------------------------------- interpreter unit --
@pytest.mark.smoke
def test_pipe_body_executes_not_special_cased():
    """`x %>% f(y)` must go through package.R's own %>% body (substitute/
    as.call/eval), not an interpreter shortcut: a pipe into a plain
    function value exercises the `(rhs)(lhs)` branch too."""
    interp = make_interp()
    interp.run_source("""
    library(distributedtpu)
    double_it <- function(v) v * 2
    a <- 21 %>% double_it()
    b <- 21 %>% double_it
    """)
    assert _scalar(interp.global_env.lookup("a")) == 42.0
    assert _scalar(interp.global_env.lookup("b")) == 42.0


@pytest.mark.smoke
def test_scope_is_lazy():
    """with_strategy_scope's expr must evaluate AFTER __enter__ (lazy
    promise) — eager args would break scope-wraps-construction."""
    interp = make_interp()
    interp.run_source("""
    library(distributedtpu)
    order_log <- c()
    fake_scope <- list(
      scope = function() list(
        `__enter__` = function() order_log <<- c(order_log, "enter"),
        `__exit__` = function(a, b, c) order_log <<- c(order_log, "exit")
      )
    )
    out <- with_strategy_scope(fake_scope, {
      order_log <<- c(order_log, "body")
      "result"
    })
    """)
    log = interp.global_env.lookup("order_log")
    assert list(log.values) == ["enter", "body", "exit"]
    assert _scalar(interp.global_env.lookup("out")) == "result"


@pytest.mark.smoke
def test_barrier_cluster_spec_port_munging():
    """strategy.R:56-60 executes for real: Spark ports stripped, new
    sequential ports, rank from the partition (1-based seq_along)."""
    import json

    interp = make_interp()
    interp.run_source("""
    library(distributedtpu)
    barrier_cluster_spec(c("h1:7001", "h2:7002", "h3:7003"), 2)
    """)
    spec = json.loads(os.environ["DTPU_CONFIG"])
    assert spec["cluster"]["worker"] == [
        "h1:8001", "h2:8002", "h3:8003"]
    assert spec["task"]["index"] == 2
    del os.environ["DTPU_CONFIG"]


# @slow (tier-1 budget, PR 17): ~8s full local.R run; R-closure crossing
# is exercised in-tier by test_local_example_executes_and_trains (loss fn
# + metrics cross the same bridge) and the callback machinery is covered
# jax-side in test_callbacks.py.
@pytest.mark.slow
def test_lr_scheduler_closure_crosses_to_python():
    """An R schedule closure handed to learning_rate_scheduler_callback
    must be callable from the Python side mid-fit (PyCallableFromR)."""
    interp = make_interp()
    interp.run_source("""
    library(distributedtpu)
    mnist <- dataset_mnist()
    model <- dtpu_model(mnist_cnn(10L))
    model %>% compile(optimizer = "sgd", learning_rate = 0.5,
                      loss = "sparse_categorical_crossentropy")
    cb <- learning_rate_scheduler_callback(function(epoch) 0.125)
    model %>% fit(mnist$train$x, mnist$train$y, batch_size = 64L,
                  epochs = 1L, steps_per_epoch = 2L, verbose = 0L,
                  callbacks = list(cb))
    lr <- model$get_learning_rate()
    """)
    assert _scalar(interp.global_env.lookup("lr")) == pytest.approx(0.125)
