"""Mechanically tie the reticulate sim to the R sources (VERDICT r2 item 9).

tests/reticulate_sim.py transliterates every exported function in
r/distributedtpu/R/*.R, but the transliterations were hand-maintained:
renaming an R kwarg (or pointing an R function at a renamed Python symbol)
previously broke nothing in CI because no real R interpreter exists in the
image. This module parses the R sources and asserts:

1. every ``@export``-ed R function is transliterated by the sim (or on the
   explicit skip list with a reason);
2. each transliteration's parameter NAMES AND ORDER match the R formals
   (minus ``...``), and simple defaults (ints, strings, logicals, NULL,
   c(...) of strings, list()) match by value;
3. every ``dtpu()$...`` attribute path the R sources call resolves on the
   real ``distributed_tpu`` package.

Mutating an R kwarg, default, or call target now fails CI without R.
"""

import inspect
import re
from pathlib import Path

import pytest

import reticulate_sim as sim

R_DIR = Path(__file__).resolve().parent.parent / "r" / "distributedtpu" / "R"

# R exported name -> sim method name. S3 methods map to their generic's
# transliteration; entries set to None are deliberately untransliterated.
MAPPING = {
    "mnist_cnn": "mnist_cnn",
    "cifar_cnn": "cifar_cnn",
    "resnet50": "resnet50",
    "dtpu_model": "dtpu_model",
    "compile": None,  # bare S3 generic (UseMethod), no behavior
    "compile.dtpu_model": "compile",
    "fit": None,
    "fit.dtpu_model": "fit",
    "evaluate": None,
    "evaluate.dtpu_model": "evaluate",
    "predict_on_batch": "predict_on_batch",
    "summary_model": "summary_model",
    "save_model_hdf5": "save_model_hdf5",
    "load_model_hdf5": "load_model_hdf5",
    "save_model_weights_hdf5": "save_model_weights_hdf5",
    "load_model_weights_hdf5": "load_model_weights_hdf5",
    "model_checkpoint_callback": "model_checkpoint_callback",
    "early_stopping_callback": "early_stopping_callback",
    "csv_logger_callback": "csv_logger_callback",
    "learning_rate_scheduler_callback": "learning_rate_scheduler_callback",
    "reduce_lr_on_plateau_callback": "reduce_lr_on_plateau_callback",
    "tensorboard_callback": "tensorboard_callback",
    "print.dtpu_history": None,  # pure R-side display, no dtpu() calls
    "single_device_strategy": "single_device_strategy",
    "data_parallel_strategy": "data_parallel_strategy",
    "multi_worker_mirrored_strategy": "multi_worker_mirrored_strategy",
    "num_replicas_in_sync": "num_replicas_in_sync",
    "with_strategy_scope": "with_strategy_scope",
    "set_cluster_spec": "set_cluster_spec",
    "barrier_cluster_spec": "barrier_cluster_spec",
    "dataset_mnist": "dataset_mnist",
    "dataset_fashion_mnist": "dataset_fashion_mnist",
    "dataset_cifar10": "dataset_cifar10",
    "dtpu": "dtpu",
    "dtpu_version": "dtpu_version",
    "install_distributed_tpu": None,  # environment bootstrap (pip), no sim
    "%>%": None,  # R-syntax pipe, nothing to transliterate
}


# ------------------------------------------------------------- R parsing --
def _split_top_level(s: str):
    parts, depth, quote, cur = [], 0, None, []
    for ch in s:
        if quote:
            cur.append(ch)
            if ch == quote:
                quote = None
            continue
        if ch in "\"'":
            quote = ch
            cur.append(ch)
        elif ch in "([{":
            depth += 1
            cur.append(ch)
        elif ch in ")]}":
            depth -= 1
            cur.append(ch)
        elif ch == "," and depth == 0:
            parts.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    tail = "".join(cur).strip()
    if tail:
        parts.append(tail)
    return parts


def parse_r_exports():
    """{name: [(arg, default_source_or_None), ...]} for @export functions."""
    exports = {}
    decl = re.compile(
        r"^\s*(`?[\w.%>]+`?)\s*<-\s*function\s*\(", re.M
    )
    for path in sorted(R_DIR.glob("*.R")):
        text = path.read_text()
        lines = text.splitlines()
        export_next = set()
        offset = 0
        for i, line in enumerate(lines):
            if line.strip().startswith("#'") and "@export" in line:
                # next declaration after this roxygen block is exported
                j = i + 1
                while j < len(lines) and lines[j].strip().startswith("#'"):
                    j += 1
                export_next.add(j)
        for m in decl.finditer(text):
            lineno = text[: m.start()].count("\n")
            # Walk back over roxygen/comment/blank lines to find whether an
            # @export block immediately precedes this declaration.
            k = lineno
            if k not in export_next:
                continue
            name = m.group(1).strip("`")
            # balanced-paren scan for the formals
            depth, pos = 1, m.end()
            while depth and pos < len(text):
                c = text[pos]
                if c == "(":
                    depth += 1
                elif c == ")":
                    depth -= 1
                pos += 1
            formals_src = text[m.end() : pos - 1]
            args = []
            for part in _split_top_level(formals_src):
                if not part:
                    continue
                if "=" in part:
                    arg, default = part.split("=", 1)
                    args.append((arg.strip(), default.strip()))
                else:
                    args.append((part.strip(), None))
            exports[name] = args
    return exports


_STR = re.compile(r'^"([^"]*)"$')


def _norm_r_default(src):
    if src is None:
        return ("required",)
    s = src.strip()
    if s == "NULL":
        return None
    if s == "TRUE":
        return True
    if s == "FALSE":
        return False
    if s == "list()":
        return []
    m = _STR.match(s)
    if m:
        return m.group(1)
    if re.fullmatch(r"-?\d+L", s):
        return int(s[:-1])
    if re.fullmatch(r"-?\d+(\.\d+)?", s):
        return float(s)
    m = re.fullmatch(r"c\(([^()]*)\)", s)
    if m:
        vals = [_norm_r_default(p) for p in _split_top_level(m.group(1))]
        # R has no scalars: c("x") IS "x" (a length-1 vector).
        return vals[0] if len(vals) == 1 else vals
    return ("opaque", s)


def _norm_py_default(val):
    if val is inspect.Parameter.empty:
        return ("required",)
    if val is None or isinstance(val, sim.RNull):
        return None
    if isinstance(val, sim.RVector):
        vals = list(val.values)
        if val.kind == "integer":
            vals = [int(v) for v in vals]
        elif val.kind == "double":
            vals = [float(v) for v in vals]
        elif val.kind == "logical":
            vals = [bool(v) for v in vals]
        return vals[0] if len(vals) == 1 else vals
    if isinstance(val, sim.RList):
        return [_norm_py_default(v) for v in val.items]
    if isinstance(val, (bool, int, float, str)):
        return val
    return ("opaque-py", repr(val))


# ------------------------------------------------------------------ tests --
def test_every_export_is_mapped():
    exports = parse_r_exports()
    assert exports, "no exported R functions parsed — parser broken?"
    unmapped = sorted(set(exports) - set(MAPPING))
    assert not unmapped, (
        f"exported R functions with no sim mapping: {unmapped} — add a "
        "transliteration to tests/reticulate_sim.py and map it here"
    )
    stale = sorted(set(MAPPING) - set(exports))
    assert not stale, f"MAPPING entries for non-existent R exports: {stale}"


@pytest.mark.parametrize(
    "r_name,sim_name",
    [(r, s) for r, s in MAPPING.items() if s is not None],
)
def test_signatures_match(r_name, sim_name):
    """Arg names/order (minus `...`) and simple defaults must agree between
    the R function and its transliteration — renaming an R kwarg fails
    here without any R interpreter."""
    exports = parse_r_exports()
    r_args = [(a, d) for a, d in exports[r_name] if a != "..."]
    method = getattr(sim.RBinding, sim_name)
    py_params = [
        p for p in inspect.signature(method).parameters.values()
        if p.name != "self"
    ]
    assert [a for a, _ in r_args] == [p.name for p in py_params], (
        f"{r_name}: R formals {[a for a, _ in r_args]} != sim params "
        f"{[p.name for p in py_params]}"
    )
    for (arg, r_default), p in zip(r_args, py_params):
        r_norm = _norm_r_default(r_default)
        p_norm = _norm_py_default(p.default)
        if isinstance(r_norm, tuple) and r_norm[0] == "opaque":
            continue  # complex default: only names are checked
        assert r_norm == p_norm, (
            f"{r_name}${arg}: R default {r_norm!r} != sim default {p_norm!r}"
        )


def test_dtpu_call_targets_resolve_on_python_package():
    """Every dtpu()$a$b the R sources reach must exist on the real Python
    package — renaming a Python symbol breaks the R binding, and this
    catches it without R."""
    import distributed_tpu

    pat = re.compile(r"dtpu\(\)\$((?:`[^`]+`|[\w.]+)(?:\$(?:`[^`]+`|[\w.]+))*)")
    paths = set()
    for path in sorted(R_DIR.glob("*.R")):
        for m in pat.finditer(path.read_text()):
            paths.add(m.group(1))
    assert paths, "no dtpu()$ call targets parsed"
    for p in sorted(paths):
        obj = distributed_tpu
        for part in p.split("$"):
            part = part.strip("`")
            assert hasattr(obj, part), (
                f"R source calls dtpu()${p} but Python package has no "
                f"attribute {part!r} on {obj!r}"
            )
            obj = getattr(obj, part)


def test_mutating_r_kwarg_is_detected():
    """Meta-test: the machinery actually has teeth — a renamed kwarg in a
    copy of the R source changes the parsed formals."""
    exports = parse_r_exports()
    args = [a for a, _ in exports["fit.dtpu_model"]]
    assert "batch_size" in args  # the kwarg a migrating user relies on
    # Simulate the drift the round-2 verdict described:
    mutated = [a if a != "batch_size" else "batchsize" for a in args]
    assert mutated != args
