"""Streaming record input: indexed record shards + parallel decode +
checkpointable iterators (ROADMAP item 5).

Contracts pinned here:
- write_records/RecordSource round-trip variable-length records exactly;
  empty records are rejected at write time.
- Corruption is LOUD: CRC mismatch and truncation raise
  RecordCorruptionError naming the shard file and record index.
- The batch stream is bit-identical for ANY decode_workers count
  (including 0 = inline), and matches the in-memory Pipeline over the
  decoded rows (same seeded permutation).
- Pipeline.state_dict()/load_state() make mid-epoch checkpoint resume
  bit-equal to an uninterrupted run — across DIFFERENT worker counts —
  and the checkpoint meta carries the cursor automatically.
- Sharded record pipelines compose with reshard: host slices assemble
  into exactly the unsharded batch, before and after a resize.

Shapes are lean (tier-1 budget); the decode-bound throughput claim lives
in ``bench.py input`` (BENCH_input.json), not here.
"""

import os
import zlib

import numpy as np
import pytest

import distributed_tpu as dtpu
from distributed_tpu.data import (
    Pipeline,
    RecordCorruptionError,
    RecordSource,
    write_records,
)

ROW_SHAPE = (4, 3)


def _make_records(tmp_path, n=100, records_per_shard=17, seed=0,
                  labels=True, name="recs"):
    """Variable-length records: [label byte][12 row bytes][random pad]."""
    rng = np.random.default_rng(seed)
    rows = rng.integers(0, 256, (n,) + ROW_SHAPE, dtype=np.uint8)
    recs = []
    for i in range(n):
        pad = bytes(rng.integers(0, 256, int(rng.integers(0, 40))).tolist())
        label = bytes([i % 256]) if labels else b"\xff"
        recs.append(label + rows[i].tobytes() + pad)
    d = tmp_path / name
    write_records(d, recs, records_per_shard=records_per_shard)
    return d, rows, recs


def _decode(b):
    row = np.frombuffer(b[1:13], np.uint8).reshape(ROW_SHAPE)
    return row.astype(np.float32), b[0]


def _decode_unlabeled(b):
    return np.frombuffer(b[1:13], np.uint8).reshape(ROW_SHAPE)


def _tiny_classifier(width=16):
    """Flatten->Dense stack: the cheapest model that can learn the synthetic
    labels — these tests pin STREAM semantics, not model quality."""
    return dtpu.nn.Sequential([
        dtpu.nn.Flatten(),
        dtpu.nn.Dense(width, activation="relu"),
        dtpu.nn.Dense(10),
    ])


class TestRecordFormat:
    def test_round_trip_variable_lengths(self, tmp_path):
        d, _, recs = _make_records(tmp_path)
        with RecordSource(d) as src:
            assert len(src) == 100
            lengths = {len(src.read(i)) for i in range(100)}
            assert len(lengths) > 1  # genuinely variable-length
            for i in (0, 16, 17, 50, 99):  # crosses shard boundaries
                assert src.read(i) == recs[i]

    def test_empty_record_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="empty"):
            write_records(tmp_path / "e", [b"ok", b""])

    def test_existing_shards_rejected(self, tmp_path):
        d, _, _ = _make_records(tmp_path)
        with pytest.raises(FileExistsError):
            write_records(d, [b"x"])

    def test_missing_sidecar_index_is_loud(self, tmp_path):
        d, _, _ = _make_records(tmp_path)
        (d / "records-00001-idx.npy").unlink()
        with pytest.raises(FileNotFoundError, match="records-00001-idx"):
            RecordSource(d)

    def test_crc_corruption_names_shard_and_record(self, tmp_path):
        d, _, _ = _make_records(tmp_path)
        path = d / "records-00001.drs"
        data = bytearray(path.read_bytes())
        data[-1] ^= 0xFF  # flip a payload byte of the shard's last record
        path.write_bytes(bytes(data))
        with RecordSource(d) as src:
            with pytest.raises(RecordCorruptionError,
                               match=r"records-00001\.drs.*record 16"):
                src.read(17 + 16)  # last record of shard 1

    def test_truncation_names_shard_and_record(self, tmp_path):
        d, _, _ = _make_records(tmp_path)
        path = d / "records-00001.drs"
        with open(path, "r+b") as f:
            f.truncate(10)
        with RecordSource(d) as src:
            with pytest.raises(RecordCorruptionError,
                               match=r"records-00001\.drs is truncated"):
                src.read(18)

    def test_bad_magic_rejected(self, tmp_path):
        d, _, _ = _make_records(tmp_path)
        path = d / "records-00000.drs"
        data = bytearray(path.read_bytes())
        data[:4] = b"JUNK"
        path.write_bytes(bytes(data))
        with pytest.raises(RecordCorruptionError, match="magic"):
            RecordSource(d)

    def test_decode_and_probe(self, tmp_path):
        d, rows, _ = _make_records(tmp_path)
        src = RecordSource(d, decode_fn=_decode)
        assert src.probe() == (ROW_SHAPE, True)
        row, label = src.decode(42)
        np.testing.assert_array_equal(row, rows[42].astype(np.float32))
        assert label == 42


class TestDecodePipeline:
    @pytest.mark.parametrize("workers", [1, 4])
    def test_stream_bit_identical_across_worker_counts(self, tmp_path,
                                                       workers):
        d, _, _ = _make_records(tmp_path)
        with Pipeline(RecordSource(d, decode_fn=_decode), None, 10,
                      seed=3) as p0, \
             Pipeline(RecordSource(d, decode_fn=_decode), None, 10,
                      seed=3, decode_workers=workers) as pw:
            assert p0.decode_workers == 0
            for _ in range(25):  # crosses pass boundaries (reshuffles)
                xa, ya = next(p0)
                xb, yb = next(pw)
                np.testing.assert_array_equal(xa, xb)
                np.testing.assert_array_equal(ya, yb)

    def test_matches_in_memory_stream(self, tmp_path):
        """Decoded record stream == the in-memory Pipeline over the same
        rows: one seeded permutation addresses every source format."""
        d, rows, _ = _make_records(tmp_path, n=96, records_per_shard=13)
        labels = np.arange(96, dtype=np.int32)
        with Pipeline(RecordSource(d, decode_fn=_decode), None, 16,
                      seed=7, decode_workers=2) as rec, \
             Pipeline(rows, labels, 16, seed=7, use_native=False,
                      scale=1.0) as mem:
            for _ in range(12):
                xa, ya = next(rec)
                xb, yb = next(mem)
                np.testing.assert_array_equal(xa, xb)
                np.testing.assert_array_equal(ya % 256, yb % 256)

    def test_unlabeled_decode_and_seek(self, tmp_path):
        d, rows, _ = _make_records(tmp_path, labels=False)
        with Pipeline(RecordSource(d, decode_fn=_decode_unlabeled), None,
                      10, seed=5, decode_workers=2) as p:
            for _ in range(7):
                next(p)
            want = [next(p) for _ in range(3)]
        with Pipeline(RecordSource(d, decode_fn=_decode_unlabeled), None,
                      10, seed=5, decode_workers=3) as q:
            q.seek(7)
            for wx, wy in want:
                gx, gy = next(q)
                np.testing.assert_array_equal(wx, gx)
                np.testing.assert_array_equal(wy, gy)  # zeros, but aligned

    def test_decode_error_surfaces_with_original_type(self, tmp_path):
        d, _, _ = _make_records(tmp_path)
        path = d / "records-00002.drs"
        data = bytearray(path.read_bytes())
        data[-1] ^= 0xFF
        path.write_bytes(bytes(data))
        with Pipeline(RecordSource(d, decode_fn=_decode), None, 100,
                      seed=0, shuffle=False, decode_workers=2) as p:
            with pytest.raises(RecordCorruptionError,
                               match=r"records-00002\.drs"):
                next(p)

    def test_decode_workers_require_records(self, tmp_path):
        x = np.zeros((32, 4, 3), np.uint8)
        with pytest.raises(ValueError, match="decode_workers"):
            Pipeline(x, None, 8, decode_workers=2)

    def test_record_source_requires_decode_fn(self, tmp_path):
        d, _, _ = _make_records(tmp_path)
        with pytest.raises(ValueError, match="decode_fn"):
            Pipeline(RecordSource(d), None, 8)

    def test_use_native_rejected_for_records(self, tmp_path):
        d, _, _ = _make_records(tmp_path)
        with pytest.raises(ValueError, match="use_native"):
            Pipeline(RecordSource(d, decode_fn=_decode), None, 8,
                     use_native=True)

    def test_labels_from_decode_exclude_y(self, tmp_path):
        d, _, _ = _make_records(tmp_path)
        with pytest.raises(ValueError, match="decode_fn"):
            Pipeline(RecordSource(d, decode_fn=_decode),
                     np.zeros(100, np.int32), 8)


class TestIteratorState:
    def test_state_dict_round_trip(self, tmp_path):
        d, _, _ = _make_records(tmp_path)

        def pipe(w):
            return Pipeline(RecordSource(d, decode_fn=_decode), None, 10,
                            seed=3, decode_workers=w)

        with pipe(2) as a:
            for _ in range(13):
                next(a)
            state = a.state_dict()
            assert state["steps_emitted"] == 13
            assert state["pass"] == 1 and state["step_in_pass"] == 3
            want = [next(a) for _ in range(3)]
        with pipe(4) as b:  # different worker count on resume
            b.load_state(state)
            for wx, wy in want:
                gx, gy = next(b)
                np.testing.assert_array_equal(wx, gx)
                np.testing.assert_array_equal(wy, gy)

    def test_load_state_validates_stream_identity(self, tmp_path):
        d, _, _ = _make_records(tmp_path)
        with Pipeline(RecordSource(d, decode_fn=_decode), None, 10,
                      seed=3) as p:
            state = p.state_dict()
        with Pipeline(RecordSource(d, decode_fn=_decode), None, 10,
                      seed=4) as q:
            with pytest.raises(ValueError, match="seed"):
                q.load_state(state)
        with Pipeline(RecordSource(d, decode_fn=_decode), None, 20,
                      seed=3) as q:
            with pytest.raises(ValueError, match="batch_size"):
                q.load_state(state)

    def test_consumed_steps_overrides_staged_ahead_cursor(self, tmp_path):
        d, _, _ = _make_records(tmp_path)
        with Pipeline(RecordSource(d, decode_fn=_decode), None, 10,
                      seed=3) as p:
            for _ in range(9):  # source staged ahead of the trained step
                next(p)
            state = p.state_dict(consumed_steps=6)
            assert state["steps_emitted"] == 6

    def test_mid_epoch_resume_bit_equal(self, tmp_path):
        """The acceptance pin: interrupt mid-epoch, resume from the
        checkpoint (which carries the iterator cursor) with a DIFFERENT
        decode worker count, finish bit-identical to uninterrupted."""
        from distributed_tpu.training.callbacks import ModelCheckpoint

        import jax

        d, _, _ = _make_records(tmp_path, n=256, records_per_shard=60,
                                name="img")

        def decode(b):
            row = np.frombuffer(b[1:13], np.uint8).reshape(4, 3, 1)
            return row.astype(np.float32) / 255.0, b[0] % 10

        def make_model():
            m = dtpu.Model(_tiny_classifier())
            m.compile(optimizer=dtpu.optim.SGD(0.05),
                      loss="sparse_categorical_crossentropy")
            m.build((4, 3, 1), seed=0)
            return m

        def pipe(w):
            return Pipeline(RecordSource(d, decode_fn=decode), None, 64,
                            seed=8, decode_workers=w)

        with pipe(0) as p1:
            m1 = make_model()
            m1.fit(p1, epochs=3, verbose=0)

        class StopAt(dtpu.callbacks.Callback):
            def on_batch_end(self, model, step, logs):
                if step == 6:  # mid-epoch-2 (4 steps/pass)
                    model.stop_training = True

        ckdir = tmp_path / "ck"
        with pipe(2) as p2:
            m2 = make_model()
            m2.fit(p2, epochs=3, verbose=0,
                   callbacks=[ModelCheckpoint(ckdir, save_freq=2),
                              StopAt()])
        assert m2.step == 6
        with pipe(4) as p3:
            m3 = make_model()
            m3.fit(p3, epochs=3, verbose=0,
                   callbacks=[ModelCheckpoint(ckdir, save_freq=2,
                                              restore=True)])
        assert m3.step == m1.step
        for a, b in zip(jax.tree_util.tree_leaves(m1.params),
                        jax.tree_util.tree_leaves(m3.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_checkpoint_meta_carries_data_state(self, tmp_path):
        from distributed_tpu.checkpoint import Checkpointer, load_npz
        from distributed_tpu.training.callbacks import ModelCheckpoint

        d, _, _ = _make_records(tmp_path, n=128, name="img2")

        def decode(b):
            row = np.frombuffer(b[1:13], np.uint8).reshape(4, 3, 1)
            return row.astype(np.float32), b[0] % 10

        m = dtpu.Model(_tiny_classifier(8))
        m.compile(optimizer=dtpu.optim.SGD(0.05),
                  loss="sparse_categorical_crossentropy")
        m.build((4, 3, 1), seed=0)
        ckdir = tmp_path / "ck2"
        with Pipeline(RecordSource(d, decode_fn=decode), None, 32,
                      seed=1) as p:
            m.fit(p, epochs=1, verbose=0,
                  callbacks=[ModelCheckpoint(ckdir, save_freq="epoch")])
        step = Checkpointer(ckdir).latest_step()
        _, meta = load_npz(ckdir / f"ckpt-{step}.npz")
        assert meta["data_state"]["steps_emitted"] == step
        assert meta["data_state"]["seed"] == 1
        assert meta["data_state"]["batch_size"] == 32


class TestReshardComposition:
    def test_sharded_streams_assemble_and_survive_resize(self, tmp_path):
        """Record-source shards of the global stream concatenate into the
        unsharded batch; a reshard mid-stream (the elastic primitive)
        keeps the assembled stream identical."""
        d, _, _ = _make_records(tmp_path, n=96, records_per_shard=20)

        def pipe(shard=None, w=2):
            return Pipeline(RecordSource(d, decode_fn=_decode), None, 12,
                            seed=4, shard=shard, decode_workers=w)

        with pipe() as full:
            stream = [next(full) for _ in range(10)]
        parts = [pipe(shard=(i, 2)) for i in range(2)]
        try:
            for step in range(4):
                fx, fy = stream[step]
                px = np.concatenate([next(p)[0] for p in parts])
                np.testing.assert_array_equal(fx, px)
            # Elastic resize 2 -> 3 at step 4: new slices of the SAME
            # global stream, cursor preserved.
            for p in parts:
                p.close()
            parts = [pipe(shard=(i, 3), w=1) for i in range(3)]
            for p in parts:
                p.seek(4)
            for step in range(4, 8):
                fx, fy = stream[step]
                px = np.concatenate([next(p)[0] for p in parts])
                np.testing.assert_array_equal(fx, px)
        finally:
            for p in parts:
                p.close()

    def test_reshard_in_place_drops_stale_decodes(self, tmp_path):
        d, _, _ = _make_records(tmp_path, n=96, records_per_shard=20)
        with Pipeline(RecordSource(d, decode_fn=_decode), None, 12,
                      seed=4, decode_workers=3) as p, \
             Pipeline(RecordSource(d, decode_fn=_decode), None, 12,
                      seed=4, shard=(1, 2), decode_workers=3) as ref:
            for _ in range(5):
                next(p)  # pool has staged shard-(0,1) slices ahead
                next(ref)
            p.reshard((1, 2))
            for _ in range(4):
                xa, ya = next(p)
                xb, yb = next(ref)
                np.testing.assert_array_equal(xa, xb)
                np.testing.assert_array_equal(ya, yb)


@pytest.mark.slow
def test_heavy_decode_matrix_bit_identical(tmp_path):
    """Heavier determinism matrix (@slow — tier-1 keeps the lean shapes):
    W in {0, 1, 2, 4, 8} x sharded/unsharded over a multi-pass stream,
    with a genuinely costly decode_fn, every stream bit-identical to
    W=0 unsharded."""
    d, _, _ = _make_records(tmp_path, n=480, records_per_shard=37)

    def costly_decode(b):
        raw = b[1:13]
        acc = zlib.crc32(b * 50)  # real per-record CPU work
        row = np.frombuffer(raw, np.uint8).reshape(ROW_SHAPE)
        return row.astype(np.float32) + np.float32((acc % 7) * 0.0), b[0]

    def pipe(w, shard=None):
        return Pipeline(RecordSource(d, decode_fn=costly_decode), None, 24,
                        seed=11, shard=shard, decode_workers=w)

    with pipe(0) as ref:
        stream = [next(ref) for _ in range(50)]  # 2.5 passes
    for w in (1, 2, 4, 8):
        with pipe(w) as p:
            for step in range(50):
                xb, yb = next(p)
                np.testing.assert_array_equal(stream[step][0], xb)
                np.testing.assert_array_equal(stream[step][1], yb)
    for w in (2, 8):
        parts = [pipe(w, shard=(i, 3)) for i in range(3)]
        try:
            for step in range(12):
                px = np.concatenate([next(p)[0] for p in parts])
                np.testing.assert_array_equal(stream[step][0], px)
        finally:
            for p in parts:
                p.close()


def test_fit_trains_from_record_pipeline(tmp_path):
    """End to end: model.fit over a record-backed streaming pipeline with
    parallel decode learns separable synthetic data."""
    x, y = dtpu.data.synthetic_images(256, (8, 8), 10, seed=5)
    d = tmp_path / "imgs"
    write_records(
        d,
        (bytes([int(l)]) + zlib.compress(img.tobytes())
         for img, l in zip(x[..., None], y)),
        records_per_shard=100,
    )

    def decode(b):
        row = np.frombuffer(zlib.decompress(b[1:]), np.uint8)
        return row.reshape(8, 8, 1).astype(np.float32) / 255.0, b[0]

    m = dtpu.Model(_tiny_classifier(32))
    m.compile(optimizer=dtpu.optim.Adam(5e-3),
              loss="sparse_categorical_crossentropy",
              metrics=["accuracy"])
    with Pipeline(RecordSource(d, decode_fn=decode), None, 64, seed=0,
                  decode_workers=2) as pipe:
        hist = m.fit(pipe, epochs=8, verbose=0)
    assert hist.history["accuracy"][-1] > 0.8, hist.history
    assert m.last_fit_telemetry["input_decode_workers"] == 2
