"""Diskless recovery (ISSUE 13): ring buddy assignment, the RAM-backed
mirror store's commit/coverage/invalidation protocol, restore-tier
selection, the in-process recovery paths (buddy restore with ZERO disk
block reads, disk fallback on redundancy loss, loss-trajectory parity),
the new fault-injection modes, and the supervisor's MTTR breakdown.

The real supervised-gang fault matrix (lose one worker -> buddy restore,
lose a buddy pair -> disk fallback, kill during refresh -> stale-mirror
rejection -> disk, stale mirror vs newer disk -> disk) runs 2-3-process
gloo gangs and is @slow; tier-1 pins every decision in-process through
the same code paths (the mirror encoding IS the sharded block layout, so
single-process restores exercise the identical reassembly).
"""

import json
import os
import shutil
import sys
from pathlib import Path

import jax
import numpy as np
import pytest

import distributed_tpu as dtpu
from distributed_tpu.checkpoint import ShardedCheckpointer
from distributed_tpu.checkpoint import sharded as sharded_lib
from distributed_tpu.resilience import (
    BuddyRedundancy,
    BuddyStore,
    FaultInjector,
    mirror_holder,
    mirror_source,
    recovery_rows,
    select_restore_tier,
)
from distributed_tpu.resilience import faults as faults_lib
from distributed_tpu.utils.profiler import redundancy_report

REPO = str(Path(__file__).resolve().parent.parent)


# ------------------------------------------------------------------ ring ----
class TestRingAssignment:
    def test_holder_source_inverse(self):
        for world in (1, 2, 3, 4, 8):
            for r in range(world):
                assert mirror_source(mirror_holder(r, world), world) == r
                assert mirror_holder(mirror_source(r, world), world) == r

    def test_ring_shape(self):
        assert mirror_holder(0, 4) == 1
        assert mirror_holder(3, 4) == 0
        assert mirror_source(0, 4) == 3
        assert mirror_holder(0, 1) == 0  # degenerate self-mirror


# ----------------------------------------------------------------- store ----
def _blocks(val, path="params/w"):
    data = np.full((4, 4), float(val), np.float32)
    key = sharded_lib._block_key(path, (0, 0), (4, 4))
    return {key: data}


def _manifest(source, world, extra=None):
    m = {"source": source, "world": world, "seed": 0, "input_shape": [4],
         "leaves": {"params/w": {"shape": [4, 4], "dtype": "float32"}}}
    m.update(extra or {})
    return m


class TestBuddyStore:
    def test_commit_protocol_and_torn_writes_invisible(self, tmp_path):
        st = BuddyStore(tmp_path)
        # A mirror dir without manifest.json (torn write) is not committed.
        torn = st._role_dir(0, "self") / "mirror-7"
        torn.mkdir(parents=True)
        np.save(torn / "block-0.npy", np.zeros(3))
        assert st.committed_steps(0, "self") == []
        # A stale tmp dir from a killed writer is invisible too.
        (st._role_dir(0, "self") / "mirror-9.tmp-123").mkdir()
        assert st.committed_steps(0, "self") == []
        st.write_mirror(0, "self", 8, _blocks(1), _manifest(0, 1))
        assert st.committed_steps(0, "self") == [8]
        # the commit swept the torn/tmp leftovers
        names = {p.name for p in st._role_dir(0, "self").iterdir()}
        assert names == {"mirror-8"}

    def test_keep_is_the_skew_tolerance(self, tmp_path):
        st = BuddyStore(tmp_path, keep=2)
        for s in (1, 2, 3):
            st.write_mirror(0, "self", s, _blocks(s), _manifest(0, 1))
        assert st.committed_steps(0, "self") == [2, 3]

    def test_invalidate_ranks_drops_whole_segments(self, tmp_path):
        st = BuddyStore(tmp_path)
        st.write_mirror(0, "self", 4, _blocks(0), _manifest(0, 2))
        st.write_mirror(1, "peer", 4, _blocks(0), _manifest(0, 2))
        assert st.invalidate_ranks([1, 5]) == [1]
        assert not st.segment(1).exists()
        assert st.committed_steps(0, "self") == [4]

    def test_available_step_requires_complete_same_step_coverage(
            self, tmp_path):
        st = BuddyStore(tmp_path)
        world = 2
        # Complete at 4: source 0 via rank-0 self, source 1 via rank-0 peer
        # (pushed by rank 1 to its holder (1+1)%2 == 0).
        st.write_mirror(0, "self", 4, _blocks(0), _manifest(0, world))
        st.write_mirror(0, "peer", 4, _blocks(1), _manifest(1, world))
        assert st.available_step() == 4
        # Newer but INCOMPLETE step never wins: source 0 refreshed at 5,
        # source 1 did not.
        st.write_mirror(0, "self", 5, _blocks(0), _manifest(0, world))
        assert st.available_step() == 4
        # Completing 5 moves the answer up.
        st.write_mirror(0, "peer", 5, _blocks(1), _manifest(1, world))
        assert st.available_step() == 5

    def test_buddy_pair_loss_leaves_no_complete_set(self, tmp_path):
        st = BuddyStore(tmp_path)
        world = 3
        # Full ring at step 6: every rank holds self + its source's peer.
        for r in range(world):
            st.write_mirror(r, "self", 6, _blocks(r), _manifest(r, world))
            src = mirror_source(r, world)
            st.write_mirror(r, "peer", 6, _blocks(src), _manifest(src, world))
        assert st.available_step() == 6
        # Lose rank 1 AND its mirror holder rank 2: shard 1's live copy
        # (rank-1 self) and its only mirror (rank-2 peer) die together.
        st.invalidate_ranks([1, mirror_holder(1, world)])
        assert st.available_step() is None

    def test_single_loss_keeps_coverage_via_the_buddy(self, tmp_path):
        st = BuddyStore(tmp_path)
        world = 3
        for r in range(world):
            st.write_mirror(r, "self", 6, _blocks(r), _manifest(r, world))
            src = mirror_source(r, world)
            st.write_mirror(r, "peer", 6, _blocks(src), _manifest(src, world))
        st.invalidate_ranks([1])  # shard 1 survives in rank-2's peer mirror
        assert st.available_step() == 6

    def test_mixed_world_steps_do_not_combine(self, tmp_path):
        """Mirrors from before a resize (world 4) must not complete a set
        with post-resize mirrors (world 2) at the same step."""
        st = BuddyStore(tmp_path)
        st.write_mirror(0, "self", 4, _blocks(0), _manifest(0, 2))
        st.write_mirror(1, "self", 4, _blocks(1), _manifest(1, 4))
        assert st.available_step() is None

    def test_bytes_held_prices_all_retained_mirrors(self, tmp_path):
        st = BuddyStore(tmp_path, keep=2)
        st.write_mirror(0, "self", 1, _blocks(1), _manifest(0, 1))
        st.write_mirror(0, "self", 2, _blocks(2), _manifest(0, 1))
        raw = 2 * 4 * 4 * 4  # two f32 (4,4) mirrors
        # file sizes: raw block bytes + the .npy headers actually resident
        assert raw <= st.bytes_held(0) <= raw + 2 * 1024
        assert st.bytes_held(3) == 0


# -------------------------------------------------------- tier selection ----
class _FakeDisk:
    def __init__(self, step):
        self._step = step

    def latest_step(self):
        return self._step


class TestTierSelection:
    def _buddy_at(self, tmp_path, step):
        st = BuddyStore(tmp_path)
        if step is not None:
            st.write_mirror(0, "self", step, _blocks(0), _manifest(0, 1))
        return BuddyRedundancy(st, rank=0, world=1)

    def test_fresh_buddy_beats_disk(self, tmp_path):
        b = self._buddy_at(tmp_path, 6)
        assert select_restore_tier(b, _FakeDisk(4)) == ("buddy", 6)
        assert select_restore_tier(b, _FakeDisk(6)) == ("buddy", 6)  # tie

    def test_stale_mirror_rejected_for_disk(self, tmp_path):
        b = self._buddy_at(tmp_path, 4)
        assert select_restore_tier(b, _FakeDisk(6)) == ("disk", 6)

    def test_missing_tiers(self, tmp_path):
        b = self._buddy_at(tmp_path, None)
        assert select_restore_tier(b, _FakeDisk(3)) == ("disk", 3)
        assert select_restore_tier(b, _FakeDisk(None)) == ("restart", None)
        assert select_restore_tier(None, _FakeDisk(None)) == ("restart", None)
        assert select_restore_tier(
            self._buddy_at(tmp_path / "b2", 2), _FakeDisk(None)
        ) == ("buddy", 2)


# ------------------------------------------------------------- in-process ----
def _data(n=64):
    x, y = dtpu.data.synthetic_images(n, (8, 8), 10, seed=3)
    return x, y


def _model():
    with dtpu.FullyShardedDataParallel().scope():
        m = dtpu.Model(dtpu.nn.Sequential([
            dtpu.nn.Flatten(),
            dtpu.nn.Dense(64, activation="relu"),
            dtpu.nn.Dense(10),
        ]))
        m.compile(optimizer=dtpu.optim.SGD(0.05, momentum=0.9),
                  loss="sparse_categorical_crossentropy")
    return m


def _loss_tracker(into):
    return dtpu.callbacks.LambdaCallback(
        on_batch_end=lambda model, step, logs: into.append(
            (int(step), float(logs["loss"]))
        )
    )


class TestInProcessRecovery:
    def test_buddy_restore_zero_disk_reads_and_parity(
            self, devices, tmp_path):
        """The tentpole contract, in-process: refresh mirrors during fit
        (async, cadence hook), kill nothing, restore a FRESH model from
        the buddy tier — zero sharded-checkpoint block reads — and
        continue training to a loss trajectory identical to the
        uninterrupted run (bit-exact here: the mirror is a byte-exact
        copy and the batch stream is (seed, step)-deterministic)."""
        x, y = _data(128)
        ref_losses = []
        m_ref = _model()
        m_ref.fit(x, y, batch_size=32, epochs=2, verbose=0, seed=0,
                  callbacks=[_loss_tracker(ref_losses)])

        store = tmp_path / "store"
        m1 = _model()
        cb = dtpu.callbacks.ModelCheckpoint(
            tmp_path / "ckpt", sharded=True, save_freq=2, async_save=True,
            buddy=store, buddy_refresh_every=1)
        m1.fit(x, y, batch_size=32, epochs=1, verbose=0, seed=0,
               callbacks=[cb])
        # telemetry pricing rode the fit
        red = m1.last_fit_telemetry["redundancy"]
        assert red["mirror_host_bytes"] > 0
        assert red["overhead_ratio"] > 1.0

        reads0 = sharded_lib.read_stats["block_reads"]
        losses2 = []
        m2 = _model()
        cb2 = dtpu.callbacks.ModelCheckpoint(
            tmp_path / "ckpt", sharded=True, save_freq=2, restore=True,
            buddy=store)
        m2.fit(x, y, batch_size=32, epochs=2, verbose=0, seed=0,
               callbacks=[cb2, _loss_tracker(losses2)])
        assert sharded_lib.read_stats["block_reads"] == reads0  # RAM only
        for a, b in zip(jax.tree_util.tree_leaves(m_ref.params),
                        jax.tree_util.tree_leaves(m2.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        ref = dict(ref_losses)
        for step, loss in losses2:  # epoch-2 steps, post-restore
            assert loss == ref[step], (step, loss, ref[step])

    def test_buddy_loss_falls_back_to_disk(self, devices, tmp_path):
        """Invalidating the only segment (the buddy died too) must route
        the SAME restore call through the disk tier — and the result is
        identical state, one save interval older at most."""
        x, y = _data()
        store = tmp_path / "store"
        m1 = _model()
        cb = dtpu.callbacks.ModelCheckpoint(
            tmp_path / "ckpt", sharded=True, save_freq=2,
            buddy=store, buddy_refresh_every=1)
        m1.fit(x, y, batch_size=32, epochs=1, verbose=0, seed=0,
               callbacks=[cb])
        BuddyStore(store).invalidate_ranks([0])

        reads0 = sharded_lib.read_stats["block_reads"]
        m2 = _model()
        cb2 = dtpu.callbacks.ModelCheckpoint(
            tmp_path / "ckpt", sharded=True, restore=True, buddy=store)
        m2.fit(x, y, batch_size=32, epochs=1, verbose=0, seed=0,
               callbacks=[cb2])
        assert sharded_lib.read_stats["block_reads"] > reads0  # disk tier
        assert m2.step == m1.step  # same final state after the replay
        for a, b in zip(jax.tree_util.tree_leaves(m1.params),
                        jax.tree_util.tree_leaves(m2.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_stale_mirror_rejected_in_restore_path(self, devices, tmp_path):
        """Mirrors frozen at an old step (refresh stopped; disk kept
        saving) must lose to the newer disk checkpoint in the REAL
        restore path, not just the selection unit."""
        x, y = _data()
        store = tmp_path / "store"
        m1 = _model()
        buddy = BuddyRedundancy(store)
        ck = ShardedCheckpointer(tmp_path / "ckpt")
        m1.fit(x, y, batch_size=32, epochs=1, steps_per_epoch=2, verbose=0,
               seed=0)
        buddy.refresh(m1)
        buddy.wait()
        m1.fit(x, y, batch_size=32, epochs=1, steps_per_epoch=2, verbose=0,
               seed=0, initial_epoch=0)
        ck.save(m1)  # disk at step 4, mirrors at step 2
        assert select_restore_tier(buddy, ck) == ("disk", 4)
        m2 = _model()
        cb2 = dtpu.callbacks.ModelCheckpoint(
            tmp_path / "ckpt", sharded=True, restore=True, buddy=store)
        m2.fit(x, y, batch_size=32, epochs=1, steps_per_epoch=4, verbose=0,
               seed=0, callbacks=[cb2])
        assert m2.step == 4

    def test_restore_into_reshards_across_strategy(self, devices, tmp_path):
        """The mirror encoding is the block layout: an FSDP-sharded
        mirror restores into a ZeRO-1 model (replicated params) through
        the same read-time reshard a disk checkpoint gets."""
        x, y = _data()
        m1 = _model()
        m1.fit(x, y, batch_size=32, epochs=1, steps_per_epoch=2, verbose=0,
               seed=0)
        buddy = BuddyRedundancy(tmp_path / "store")
        buddy.refresh(m1)
        buddy.wait()

        with dtpu.ZeroDataParallel().scope():
            m2 = dtpu.Model(dtpu.nn.Sequential([
                dtpu.nn.Flatten(),
                dtpu.nn.Dense(64, activation="relu"),
                dtpu.nn.Dense(10),
            ]))
            m2.compile(optimizer=dtpu.optim.SGD(0.05, momentum=0.9),
                       loss="sparse_categorical_crossentropy")
        m2.build((8, 8))
        step = BuddyRedundancy(tmp_path / "store").restore_into(m2)
        assert step == m1.step
        from jax.sharding import PartitionSpec

        leaf = m2.params["dense"]["kernel"]
        assert leaf.sharding.spec == PartitionSpec()  # live strategy wins
        for a, b in zip(jax.tree_util.tree_leaves(m1.params),
                        jax.tree_util.tree_leaves(m2.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_refresh_failure_degrades_not_raises(self, devices, tmp_path,
                                                 monkeypatch):
        x, y = _data()
        m = _model()
        buddy = BuddyRedundancy(tmp_path / "store", async_refresh=False)
        m.fit(x, y, batch_size=32, epochs=1, steps_per_epoch=1, verbose=0,
              seed=0)
        monkeypatch.setattr(
            buddy.store, "write_mirror",
            lambda *a, **k: (_ for _ in ()).throw(OSError("store full")))
        buddy.refresh(m)  # must not raise
        assert isinstance(buddy.last_refresh_error, OSError)
        assert buddy.available_step() is None  # tier degraded, run alive


# ------------------------------------------------------------ fault modes ----
class TestNewFaultModes:
    def test_from_env_parses_new_modes(self, monkeypatch):
        monkeypatch.setenv("DTPU_FAULT", "buddy_kill:at_step=7,rank=1")
        f = FaultInjector.from_env()
        assert f.mode == "buddy_kill" and f.at_step == 7 and f.rank == 1
        monkeypatch.setenv("DTPU_FAULT", "kill_during_refresh:at_step=3")
        f = FaultInjector.from_env()
        assert f.mode == "kill_during_refresh" and f.at_step == 3

    def test_pair_modes_require_concrete_rank(self):
        with pytest.raises(ValueError, match="rank"):
            FaultInjector("buddy_kill", rank=None)
        with pytest.raises(ValueError, match="rank"):
            FaultInjector("kill_during_refresh", rank=None)

    def test_buddy_kill_arms_the_pair(self, monkeypatch):
        f = FaultInjector("buddy_kill", at_step=5, rank=1)
        monkeypatch.setattr(jax, "process_count", lambda: 4)
        for me, armed in ((0, False), (1, True), (2, True), (3, False)):
            monkeypatch.setattr(jax, "process_index", lambda me=me: me)
            assert f._armed() is armed

    def test_buddy_kill_markers_are_per_rank(self, monkeypatch, tmp_path):
        """Both pair members must fire: the first one's once-marker must
        not disarm the second."""
        marker = tmp_path / "once"
        monkeypatch.setattr(jax, "process_count", lambda: 4)
        monkeypatch.setattr(jax, "process_index", lambda: 1)
        f = FaultInjector("buddy_kill", at_step=5, rank=1,
                          once_marker=marker)
        assert f._marker_path().name == "once.rank1"
        # rank 2 (the mirror holder) checks ITS marker, not rank 1's
        f._marker_path().touch()
        assert not f._armed()
        monkeypatch.setattr(jax, "process_index", lambda: 2)
        assert f._armed()

    def test_kill_during_refresh_fires_mid_refresh_only(self, monkeypatch,
                                                        tmp_path):
        exits = []
        monkeypatch.setattr(os, "_exit", lambda code: exits.append(code))
        monkeypatch.setattr(jax, "process_index", lambda: 0)
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        f = FaultInjector("kill_during_refresh", at_step=5, rank=0,
                          once_marker=tmp_path / "m")
        f.on_batch_end(None, 9, {})  # step-driven path must ignore it
        assert exits == [] and not f.fired
        f.on_train_begin(None)  # registers with the refresh hook
        faults_lib.fire_refresh_kill(3)  # below at_step: inert
        assert exits == []
        faults_lib.fire_refresh_kill(5)
        assert exits == [17] and f.fired
        assert (tmp_path / "m").exists()
        faults_lib.fire_refresh_kill(6)  # fired once, stays inert
        assert exits == [17]
        f.on_train_end(None, None)  # deregisters
        assert f not in faults_lib._REFRESH_FAULTS

    def test_corrupt_latest_checkpoint_handles_sharded_dirs(
            self, devices, tmp_path):
        from distributed_tpu.resilience import corrupt_latest_checkpoint

        m = _model()
        m.build((8, 8))
        ck = ShardedCheckpointer(tmp_path)
        ck.save(m, step=3)
        ck.save(m, step=5)
        hit = corrupt_latest_checkpoint(tmp_path)
        assert hit == tmp_path / "ckpt-5" / "proc-0.npz"
        m2 = _model()
        m2.build((8, 8))
        assert ck.restore_into(m2) == 3  # fell back past the garbage


# ------------------------------------------------------- MTTR breakdown ----
class TestRecoveryRows:
    def _events(self):
        t = 100.0
        return [
            {"event": "fault_injected", "ts": t + 1.0, "mode": "kill"},
            {"event": "attempt_end", "ts": t + 3.0, "attempt": 1,
             "ok": False},
            {"event": "attempt_start", "ts": t + 3.1, "attempt": 2},
            {"event": "restore_begin", "ts": t + 5.0, "rank": 1},
            {"event": "restore_begin", "ts": t + 5.5, "rank": 0},
            {"event": "restore_end", "ts": t + 6.0, "rank": 0,
             "tier": "buddy", "step": 4, "disk_block_reads": 0},
            {"event": "post_restore_step", "ts": t + 7.5, "rank": 0},
            {"event": "attempt_end", "ts": t + 9.0, "attempt": 2, "ok": True},
        ]

    def test_breakdown(self):
        rows = recovery_rows(self._events())
        assert len(rows) == 1
        row = rows[0]
        assert row["failed_attempt"] == 1 and row["recovered_attempt"] == 2
        assert row["detect_s"] == 2.0
        assert row["gang_reform_s"] == 2.5   # rank-0 restore_begin
        assert row["restore_s"] == 0.5
        assert row["recompile_s"] == 1.5
        assert row["restore_tier"] == "buddy" and row["restore_step"] == 4
        assert row["disk_block_reads"] == 0
        assert row["total_to_first_step_s"] == 4.5

    def test_tolerates_missing_worker_events(self):
        events = [e for e in self._events()
                  if e["event"] in ("attempt_end", "attempt_start")]
        (row,) = recovery_rows(events)
        assert row["restore_s"] is None and row["restore_tier"] is None

    def test_no_relaunch_no_row(self):
        events = [{"event": "attempt_end", "ts": 1.0, "attempt": 1,
                   "ok": False}]
        assert recovery_rows(events) == []


def test_redundancy_report_math():
    rep = redundancy_report(100, 50, world=4)
    assert rep["overhead_ratio"] == 1.5 and rep["world"] == 4
    assert redundancy_report(0, 10)["overhead_ratio"] is None


# ------------------------------------------------------ gang fault matrix ----
def _losses_by_step(events):
    """step -> loss from rank-0 step_mark events; later attempts win."""
    out = {}
    for e in sorted((e for e in events if e["event"] == "step_mark"),
                    key=lambda e: e["attempt"]):
        if e.get("loss") is not None:
            out[e["step"]] = e["loss"]
    return out


def _matrix_gang(tmp, **kw):
    sys.path.insert(0, REPO)
    import bench

    kw.setdefault("width", 192)
    kw.setdefault("steps", 8)
    kw.setdefault("record_loss", True)
    kw.setdefault("timeout", 900.0)
    res, events, store = bench._recovery_gang(tmp, **kw)
    shutil.rmtree(store, ignore_errors=True)
    return res, events


def _assert_parity(tmp, events, steps=8, **ref_kw):
    """Post-recovery loss-trajectory parity at the PR 7 tolerance: the
    recovered run's per-step losses equal the uninterrupted run's."""
    ref_res, ref_events = _matrix_gang(tmp, fault=None, steps=steps,
                                       **ref_kw)
    assert ref_res.ok and ref_res.attempts == 1
    got, ref = _losses_by_step(events), _losses_by_step(ref_events)
    assert set(got) == set(ref) == set(range(1, steps + 1))
    traj = np.array([got[s] for s in range(1, steps + 1)])
    ref_traj = np.array([ref[s] for s in range(1, steps + 1)])
    np.testing.assert_allclose(traj, ref_traj, rtol=2e-5, atol=0)


def _recovery(events):
    return next(e for e in events if e["event"] == "recovery")


@pytest.mark.slow
def test_gang_single_loss_buddy_restore(tmp_path):
    """ACCEPTANCE: kill one of two FSDP workers mid-run; the relaunched
    gang restores the WHOLE state from the surviving segment's mirrors —
    tier buddy, zero disk-block reads — and the completed run's loss
    trajectory matches the uninterrupted one."""
    res, events = _matrix_gang(tmp_path / "run",
                               fault="kill:at_step=5,rank=1")
    assert res.ok, [(r.index, r.error) for r in res.results]
    row = _recovery(events)
    assert row["restore_tier"] == "buddy"
    assert row["disk_block_reads"] == 0
    inv = next(e for e in events
               if e["event"] == "buddy_segments_invalidated")
    assert inv["ranks"] == [1]
    _assert_parity(tmp_path / "ref", events)


@pytest.mark.slow
def test_gang_buddy_pair_loss_disk_fallback(tmp_path):
    """Kill a worker AND its mirror holder (buddy_kill): the shard's live
    copy and its only mirror die together, so the recovery must come from
    the disk checkpoint — and still complete with trajectory parity."""
    res, events = _matrix_gang(
        tmp_path / "run", world=3, global_batch=48,
        fault="buddy_kill:at_step=5,rank=1")
    assert res.ok, [(r.index, r.error) for r in res.results]
    row = _recovery(events)
    assert row["restore_tier"] == "disk"
    assert row["disk_block_reads"] > 0
    inv = next(e for e in events
               if e["event"] == "buddy_segments_invalidated")
    assert inv["ranks"] == [1, 2]  # rank 1 and holder (1+1)%3
    _assert_parity(tmp_path / "ref", events, world=3, global_batch=48)


@pytest.mark.slow
def test_gang_kill_during_refresh_stale_rejection(tmp_path):
    """Die MID-refresh (self committed, peer push not): the store keeps
    only an older complete set while the disk checkpoint is newer — the
    stale mirrors must be rejected for the disk tier."""
    res, events = _matrix_gang(
        tmp_path / "run", fault="kill_during_refresh:at_step=8,rank=1",
        refresh_every=4, save_freq=1, steps=10)
    assert res.ok, [(r.index, r.error) for r in res.results]
    assert any(e["event"] == "buddy_refresh" for e in events)  # tier was live
    row = _recovery(events)
    assert row["restore_tier"] == "disk"
    assert row["restore_step"] > 4  # newer than the stale complete set
    _assert_parity(tmp_path / "ref", events, refresh_every=4, save_freq=1,
                   steps=10)


@pytest.mark.slow
def test_gang_stale_mirror_disk_wins(tmp_path):
    """Lose a worker while the mirrors are legitimately STALE (coarse
    refresh cadence vs per-step synchronous saves): selection must prefer
    the newer disk step over the older complete mirror set."""
    res, events = _matrix_gang(
        tmp_path / "run", fault="kill:at_step=7,rank=1",
        refresh_every=2, save_freq=1, sync_save=True)
    assert res.ok, [(r.index, r.error) for r in res.results]
    row = _recovery(events)
    assert row["restore_tier"] == "disk"
    assert row["restore_step"] == 7  # sync save at the kill step
    _assert_parity(tmp_path / "ref", events, refresh_every=2, save_freq=1,
                   sync_save=True)
