"""nn.Remat (gradient checkpointing) and the ViT model family.

Remat's contract is transparency: identical outputs, grads, param-tree
paths, sharding hints and decode behavior — only the XLA schedule changes
(a remat primitive appears in the jaxpr).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import distributed_tpu as dtpu
from distributed_tpu import nn


def _block(remat):
    inner = nn.Sequential(
        [nn.LayerNorm(), nn.Dense(32, activation="gelu"), nn.Dense(16)],
        name="main",
    )
    return nn.Remat(inner) if remat else inner


class TestRemat:
    def test_outputs_grads_and_tree_identical(self):
        plain, wrapped = _block(False), _block(True)
        params, state, _ = plain.init(jax.random.PRNGKey(0), (16,))
        params_w, _, _ = wrapped.init(jax.random.PRNGKey(0), (16,))
        assert jax.tree_util.tree_structure(params) == \
            jax.tree_util.tree_structure(params_w)

        x = jnp.asarray(
            np.random.default_rng(0).standard_normal((4, 16)), jnp.float32
        )

        def loss_plain(p):
            return jnp.sum(plain.apply(p, {}, x)[0] ** 2)

        def loss_wrapped(p):
            return jnp.sum(wrapped.apply(p, {}, x)[0] ** 2)

        np.testing.assert_allclose(
            loss_plain(params), loss_wrapped(params), rtol=1e-6
        )
        gp = jax.grad(loss_plain)(params)
        gw = jax.grad(loss_wrapped)(params)
        for a, b in zip(
            jax.tree_util.tree_leaves(gp), jax.tree_util.tree_leaves(gw)
        ):
            np.testing.assert_allclose(a, b, atol=1e-6, rtol=1e-5)

    def test_remat_primitive_in_jaxpr(self):
        wrapped = _block(True)
        params, _, _ = wrapped.init(jax.random.PRNGKey(0), (16,))
        x = jnp.zeros((2, 16))
        jaxpr = jax.make_jaxpr(
            lambda p: jax.grad(
                lambda q: jnp.sum(wrapped.apply(q, {}, x)[0])
            )(p)
        )(params)
        assert "remat" in str(jaxpr)

    def test_transparent_name_and_hints(self):
        inner = nn.Dense(8, shard="col")
        wrapped = nn.Remat(inner)
        assert wrapped.default_name() == inner.default_name()
        assert wrapped.sharding_hints() == inner.sharding_hints()

    def test_explicit_inner_name_survives_wrapping(self):
        """Toggling remat must not change checkpoint paths — an explicitly
        named layer keeps its name through the wrapper."""
        plain = nn.Sequential([nn.Dense(8, name="head")])
        wrapped = nn.Sequential([nn.Remat(nn.Dense(8, name="head"))])
        p1, _, _ = plain.init(jax.random.PRNGKey(0), (4,))
        p2, _, _ = wrapped.init(jax.random.PRNGKey(0), (4,))
        assert set(p1) == set(p2) == {"head"}
        # Duplicate-name detection still fires through the wrapper.
        with pytest.raises(ValueError, match="Duplicate"):
            nn.Sequential([
                nn.Remat(nn.Dense(8, name="x")),
                nn.Remat(nn.Dense(8, name="x")),
            ])

    # @slow (tier-1 budget, PR 17): ~8s composition cross-product; remat
    # training numerics stay in-tier via test_lm_remat_training_parity and
    # pipeline numerics via test_pp_matches_single_device[pp2]
    # (test_pipeline_parallel.py) — this pins their product only.
    @pytest.mark.slow
    def test_pipelined_remat_matches_plain_pipeline(self):
        """transformer_lm(pipeline=True, remat=True) must train identically
        to the un-remat pipelined model (remat only reschedules)."""
        x = np.random.default_rng(3).integers(0, 32, (8, 8)).astype(np.int32)
        y = np.random.default_rng(4).integers(0, 32, (8, 8)).astype(np.int32)
        losses = []
        for remat in (False, True):
            m = dtpu.Model(
                dtpu.models.transformer_lm(
                    32, num_layers=2, d_model=16, num_heads=2, max_len=8,
                    pipeline=True, remat=remat,
                )
            )
            m.compile(optimizer=dtpu.optim.Adam(1e-3),
                      loss="sparse_categorical_crossentropy")
            losses.append(
                m.fit(x, y, batch_size=8, epochs=2, verbose=0, seed=0)
                .history["loss"]
            )
        np.testing.assert_allclose(losses[0], losses[1], rtol=1e-5)

    def test_lm_remat_training_parity(self):
        """transformer_lm(remat=True) trains to the same losses as without
        (same seed, same data) — remat must not perturb numerics."""
        x = np.random.default_rng(0).integers(0, 32, (8, 12)).astype(np.int32)
        y = np.random.default_rng(1).integers(0, 32, (8, 12)).astype(np.int32)
        hists = []
        for remat in (False, True):
            m = dtpu.Model(
                dtpu.models.transformer_lm(
                    32, num_layers=2, d_model=16, num_heads=2, max_len=12,
                    remat=remat,
                )
            )
            m.compile(optimizer=dtpu.optim.Adam(1e-3),
                      loss="sparse_categorical_crossentropy")
            hists.append(
                m.fit(x, y, batch_size=8, epochs=3, verbose=0, seed=0)
                .history["loss"]
            )
        np.testing.assert_allclose(hists[0], hists[1], rtol=1e-5)

    def test_remat_lm_generate_works(self):
        m = dtpu.Model(
            dtpu.models.transformer_lm(
                32, num_layers=1, d_model=16, num_heads=2, max_len=16,
                remat=True,
            )
        )
        m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
        m.build((8,))
        out = m.generate(np.array([[1, 2]], np.int32), 4, temperature=0.0)
        assert out.shape == (1, 6)


class TestViT:
    def test_shapes_and_param_structure(self):
        module = dtpu.models.vit(
            10, image_size=32, patch_size=8, num_layers=2, d_model=32,
            num_heads=4,
        )
        params, state, out = module.init(jax.random.PRNGKey(0), (32, 32, 3))
        assert out == (10,)
        x = jnp.zeros((2, 32, 32, 3))
        logits, _ = module.apply(params, {}, x)
        assert logits.shape == (2, 10)

    def test_indivisible_patch_raises(self):
        with pytest.raises(ValueError, match="divisible"):
            dtpu.models.vit(10, image_size=30, patch_size=16)

    def test_named_sizes(self):
        m = dtpu.models.vit_tiny(10, image_size=32, patch_size=16)
        _, _, out = m.init(jax.random.PRNGKey(0), (32, 32, 3))
        assert out == (10,)

    # @slow (tier-1 budget, PR 17): ~6s convergence drive; ViT wiring
    # stays pinned in-tier (shapes/param structure, named sizes, TP
    # variants, scan-vs-unrolled param count + training), and separable-
    # data convergence is covered in-tier by the mnist/transformer drives.
    @pytest.mark.slow
    def test_learns_separable_data(self):
        x, y = dtpu.data.synthetic_images(256, (16, 16), 4, 0)
        x = np.repeat(x[..., None], 3, axis=-1).astype(np.float32) / 255.0
        model = dtpu.Model(
            dtpu.models.vit(
                4, image_size=16, patch_size=4, num_layers=2, d_model=32,
                num_heads=4,
            )
        )
        model.compile(optimizer=dtpu.optim.Adam(3e-3),
                      loss="sparse_categorical_crossentropy",
                      metrics=["accuracy"])
        hist = model.fit(x, y.astype(np.int32), batch_size=64, epochs=15,
                         verbose=0)
        assert hist.history["accuracy"][-1] > 0.8, hist.history["accuracy"][-3:]

    def test_tp_hints_flow_from_blocks(self):
        module = dtpu.models.vit(
            10, image_size=32, patch_size=8, num_layers=1, d_model=32,
            num_heads=4,
        )
        hints = module.sharding_hints()
        flat = str(hints)
        assert "col" in flat and "row" in flat  # Megatron roles present

    def test_vit_under_tensor_parallel(self, devices):
        strategy = dtpu.DataTensorParallel(devices=devices, model_parallel=2)
        with strategy.scope():
            model = dtpu.Model(
                dtpu.models.vit(
                    10, image_size=16, patch_size=4, num_layers=1,
                    d_model=32, num_heads=4,
                )
            )
            model.compile(optimizer=dtpu.optim.Adam(1e-3),
                          loss="sparse_categorical_crossentropy")
        x = np.zeros((8, 16, 16, 3), np.float32)
        y = np.zeros((8,), np.int32)
        hist = model.fit(x, y, batch_size=8, epochs=1, verbose=0)
        assert len(hist.history["loss"]) == 1


def test_vit_scan_matches_unrolled_param_count_and_trains():
    import distributed_tpu as dtpu

    kw = dict(image_size=16, patch_size=4, num_layers=3, d_model=32,
              num_heads=4)
    pu, _, _ = dtpu.models.vit(10, **kw).init(jax.random.PRNGKey(0),
                                              (16, 16, 3))
    ps, _, _ = dtpu.models.vit(10, scan=True, **kw).init(
        jax.random.PRNGKey(0), (16, 16, 3))
    size = lambda t: sum(int(np.prod(l.shape))
                         for l in jax.tree_util.tree_leaves(t))
    assert size(pu) == size(ps)

    m = dtpu.Model(dtpu.models.vit(10, scan=True, remat=True, **kw))
    m.compile(optimizer=dtpu.optim.Adam(1e-3),
              loss="sparse_categorical_crossentropy")
    m.build((16, 16, 3))
    x = np.random.default_rng(0).standard_normal((4, 16, 16, 3)).astype(
        np.float32)
    y = np.arange(4, dtype=np.int32) % 10
    h = m.fit(x, y, batch_size=4, epochs=1, steps_per_epoch=2, verbose=0)
    assert np.isfinite(h.history["loss"]).all()
