"""Resilience subsystem: supervisor restart/resume, fault injection,
backoff/budget policy, preemption handling, corrupt-checkpoint fallback.

The acceptance bar (ISSUE 2): a fault-injected worker kill mid-epoch is
followed by automatic supervisor restart + checkpoint resume, and the
finished run's params match an uninterrupted run's. The full fault matrix
(kill / hang / slow-heartbeat / corrupt-checkpoint) is @slow; one kill
end-to-end plus all policy/unit coverage stays in tier-1.
"""

import json
import os
import signal
import socket
import sys
import textwrap
import types
from pathlib import Path

import numpy as np
import pytest

import distributed_tpu as dtpu
from distributed_tpu.cluster import net
from distributed_tpu.launch import LocalLauncher, WorkerResult
from distributed_tpu.resilience import (
    PREEMPTED_EXIT_CODE,
    FaultInjector,
    PreemptionHandler,
    RestartPolicy,
    Supervisor,
    corrupt_latest_checkpoint,
    read_resume_marker,
)
from distributed_tpu.training.callbacks import LambdaCallback, ModelCheckpoint
from distributed_tpu.utils.events import EventLog, read_events

REPO = str(Path(__file__).resolve().parent.parent)


def _small_model():
    model = dtpu.Model(dtpu.models.mnist_cnn())
    model.compile(
        optimizer=dtpu.optim.SGD(0.05),
        loss="sparse_categorical_crossentropy",
        metrics=["accuracy"],
    )
    return model


def _data(n=128):
    x, y = dtpu.data.synthetic_images(n, (28, 28), 10, seed=3)
    return x[..., None].astype(np.float32) / 255.0, y


# ---------------------------------------------------------------- policy ----
class TestRestartPolicy:
    @pytest.mark.smoke
    def test_backoff_schedule_is_bounded_exponential(self):
        p = RestartPolicy(backoff=1.0, backoff_factor=2.0, backoff_max=5.0)
        assert [p.delay(i) for i in (1, 2, 3, 4, 5)] == [1, 2, 4, 5, 5]

    def test_budget(self):
        p = RestartPolicy(max_restarts=2)
        assert p.allows_restart(0) and p.allows_restart(1)
        assert not p.allows_restart(2)
        assert RestartPolicy(max_restarts=0).allows_restart(0) is False

    def test_preemption_cap(self):
        p = RestartPolicy(max_preemptions=1)
        assert p.allows_preemption_restart(0)
        assert not p.allows_preemption_restart(1)

    def test_validation(self):
        with pytest.raises(ValueError):
            RestartPolicy(max_restarts=-1)
        with pytest.raises(ValueError):
            RestartPolicy(backoff_factor=0.5)
        with pytest.raises(ValueError):
            RestartPolicy(backoff=2.0, backoff_max=1.0)
        with pytest.raises(ValueError):
            RestartPolicy().delay(0)


# ------------------------------------------------------------- event log ----
class TestEventLog:
    def test_roundtrip_and_torn_tail(self, tmp_path):
        log = EventLog(tmp_path / "ev.jsonl")
        log.emit("restart", attempt=2, delay=1.5)
        log.emit("run_complete", attempts=3)
        # A writer killed mid-append leaves a torn line; reads must skip it.
        with open(log.path, "a") as f:
            f.write('{"event": "torn')
        events = log.read()
        assert [e["event"] for e in events] == ["restart", "run_complete"]
        assert events[0]["attempt"] == 2 and "ts" in events[0]

    def test_ambient_emit_noop_without_env(self, monkeypatch):
        from distributed_tpu.utils import events as ev

        monkeypatch.delenv(ev.ENV_VAR, raising=False)
        assert ev.emit("whatever") is None

    def test_ambient_emit_with_env(self, monkeypatch, tmp_path):
        from distributed_tpu.utils import events as ev

        path = tmp_path / "amb.jsonl"
        monkeypatch.setenv(ev.ENV_VAR, str(path))
        assert ev.emit("ping", x=1)["x"] == 1
        assert read_events(path)[0]["event"] == "ping"


# ------------------------------------------------------- net preflight ------
class TestPreflightBackoff:
    def test_backoff_schedule(self):
        assert net.backoff_schedule(1) == []
        assert net.backoff_schedule(5, backoff=0.5, backoff_max=2.0) == [
            0.5, 1.0, 2.0, 2.0,
        ]
        with pytest.raises(ValueError):
            net.backoff_schedule(0)

    def test_retries_until_worker_boots(self, monkeypatch):
        """A still-booting worker (connect timeouts, then up) passes the
        preflight instead of failing the first probe."""
        calls, sleeps = [], []

        class _Conn:
            def __enter__(self):
                return self

            def __exit__(self, *a):
                return False

        def fake_create(addr, timeout=None):
            calls.append(addr)
            if len(calls) < 3:
                raise socket.timeout("still booting")
            return _Conn()

        monkeypatch.setattr(net.socket, "create_connection", fake_create)
        ok = net.check_reachable("10.9.9.9:8476", timeout=0.1, attempts=4,
                                 backoff=0.1, _sleep=sleeps.append)
        assert ok and len(calls) == 3
        assert sleeps == [0.1, 0.2]  # exponential, only between failures

    def test_refused_is_up_without_retry(self, monkeypatch):
        sleeps = []

        def fake_create(addr, timeout=None):
            raise ConnectionRefusedError

        monkeypatch.setattr(net.socket, "create_connection", fake_create)
        assert net.check_reachable("h:1", attempts=5, _sleep=sleeps.append)
        assert sleeps == []  # refusal means up: answer immediately

    def test_still_down_after_budget(self, monkeypatch):
        sleeps = []

        def fake_create(addr, timeout=None):
            raise OSError("no route")

        monkeypatch.setattr(net.socket, "create_connection", fake_create)
        assert not net.check_reachable("h:1", attempts=3, backoff=0.1,
                                       _sleep=sleeps.append)
        assert len(sleeps) == 2  # attempts-1 sleeps, then give up


# ------------------------------------------- checkpoint latest + corrupt ----
class TestLatestPointerAndCorruptFallback:
    def _trained(self, tmp_path, steps=(2, 4)):
        model = _small_model()
        model.build((28, 28, 1), seed=0)
        ckpt = dtpu.Checkpointer(tmp_path, keep=10)
        for s in steps:
            ckpt.save(model, step=s)
        return model, ckpt

    def test_pointer_written_atomically_and_read(self, tmp_path):
        _, ckpt = self._trained(tmp_path)
        pointer = tmp_path / "latest"
        assert json.loads(pointer.read_text()) == {"step": 4}
        assert ckpt.latest_step() == 4
        assert not list(tmp_path.glob("*.tmp"))  # no tmp litter

    def test_corrupt_pointer_falls_back_to_scan(self, tmp_path):
        _, ckpt = self._trained(tmp_path)
        (tmp_path / "latest").write_text('{"st')  # torn write simulation
        assert ckpt.latest_step() == 4

    def test_stale_pointer_loses_to_newer_file(self, tmp_path):
        # Crash between npz rename and pointer write: ckpt-6 exists,
        # pointer still says 4 — the newer complete file wins.
        model, ckpt = self._trained(tmp_path)
        from distributed_tpu.checkpoint.core import save_npz

        save_npz(ckpt._path(6), {"params": model.params,
                                 "state": {}, "opt_state": model.opt_state},
                 {"step": 6, "seed": 0, "input_shape": [28, 28, 1]})
        assert json.loads((tmp_path / "latest").read_text())["step"] == 4
        assert ckpt.latest_step() == 6

    def test_corrupt_latest_restores_previous_step(self, tmp_path, monkeypatch):
        from distributed_tpu.utils import events as ev

        monkeypatch.setenv(ev.ENV_VAR, str(tmp_path / "ev.jsonl"))
        _, ckpt = self._trained(tmp_path)
        assert corrupt_latest_checkpoint(tmp_path).name == "ckpt-4.npz"
        assert ckpt.is_valid(2) and not ckpt.is_valid(4)
        assert ckpt.latest_valid_step() == 2

        fresh = _small_model()
        step = dtpu.Checkpointer(tmp_path).restore_into(fresh)
        assert step == 2
        kinds = [e["event"] for e in read_events(tmp_path / "ev.jsonl")]
        assert "corrupt_checkpoint_skipped" in kinds

    def test_explicit_corrupt_step_raises(self, tmp_path):
        self._trained(tmp_path)
        corrupt_latest_checkpoint(tmp_path)
        fresh = _small_model()
        with pytest.raises((ValueError, OSError, KeyError)):
            dtpu.Checkpointer(tmp_path).restore_into(fresh, step=4)

    def test_all_corrupt_raises_filenotfound(self, tmp_path):
        self._trained(tmp_path, steps=(3,))
        corrupt_latest_checkpoint(tmp_path)
        fresh = _small_model()
        with pytest.raises(FileNotFoundError, match="corrupt"):
            dtpu.Checkpointer(tmp_path).restore_into(fresh)


# --------------------------------------------------------- fault injector ---
class TestFaultInjector:
    def test_from_env(self, monkeypatch, tmp_path):
        monkeypatch.setenv(
            "DTPU_FAULT", "kill:at_step=7,rank=all,exit_code=9")
        monkeypatch.setenv("DTPU_FAULT_MARKER", str(tmp_path / "m"))
        f = FaultInjector.from_env()
        assert (f.mode, f.at_step, f.rank, f.exit_code) == ("kill", 7, None, 9)
        assert f.once_marker == tmp_path / "m"

    def test_from_env_absent(self, monkeypatch):
        monkeypatch.delenv("DTPU_FAULT", raising=False)
        assert FaultInjector.from_env() is None

    def test_bad_mode_and_keys(self, monkeypatch):
        with pytest.raises(ValueError):
            FaultInjector("explode")
        with pytest.raises(ValueError):
            FaultInjector("corrupt_checkpoint")  # needs directory=
        monkeypatch.setenv("DTPU_FAULT", "kill:frequency=2")
        with pytest.raises(ValueError):
            FaultInjector.from_env()

    def test_once_marker_disarms(self, tmp_path):
        marker = tmp_path / "fired"
        marker.touch()
        f = FaultInjector("kill", at_step=0, once_marker=marker)
        # Would os._exit if armed; reaching the next line proves disarm.
        f.on_batch_end(types.SimpleNamespace(step=5), 5, {})
        assert not f.fired


# -------------------------------------------------------- supervisor unit ---
def _ok(i=0):
    return WorkerResult(index=i, ok=True, value="fine", exit_code=0)


def _fail(i=0, code=1):
    return WorkerResult(index=i, ok=False, error=f"exit code {code}",
                        exit_code=code)


def _preempted(i=0):
    return WorkerResult(index=i, ok=False,
                        error=f"exit code {PREEMPTED_EXIT_CODE}",
                        exit_code=PREEMPTED_EXIT_CODE)


class FakeLauncher:
    """Scripted launcher: each entry is a result list or 'raise'."""

    def __init__(self, script):
        self.script = list(script)
        self.env_extra = {}
        self.seen_env = []

    def run(self, argv, num_workers, **kw):
        self.seen_env.append(dict(self.env_extra))
        out = self.script.pop(0)
        if out == "raise":
            raise RuntimeError("preflight failed for relaunch")
        return out


class TestSupervisorUnit:
    def test_restart_until_success_with_backoff(self, tmp_path):
        sleeps = []
        launcher = FakeLauncher([[_fail()], [_fail()], [_ok()]])
        sup = Supervisor(
            ["prog"], 1, launcher=launcher,
            policy=RestartPolicy(max_restarts=3, backoff=0.5,
                                 backoff_factor=2.0, backoff_max=10.0),
            event_log=EventLog(tmp_path / "ev.jsonl"),
            sleep=sleeps.append,
        )
        out = sup.run(timeout=5)
        assert out.ok and out.attempts == 3 and out.restarts_used == 2
        assert sleeps == [0.5, 1.0]  # exponential between relaunches
        kinds = [e["event"] for e in read_events(tmp_path / "ev.jsonl")]
        assert kinds.count("attempt_start") == 3
        assert kinds.count("restart") == 2
        assert kinds[-1] == "run_complete"
        # Per-attempt env: the attempt counter and event-log path reach
        # workers through the launcher's env injection.
        assert [e["DTPU_ATTEMPT"] for e in launcher.seen_env] == ["1", "2", "3"]
        assert all(e["DTPU_EVENT_LOG"] == str(tmp_path / "ev.jsonl")
                   for e in launcher.seen_env)

    def test_budget_exhaustion(self, tmp_path):
        launcher = FakeLauncher([[_fail()]] * 3)
        sup = Supervisor(["prog"], 1, launcher=launcher,
                         policy=RestartPolicy(max_restarts=1, backoff=0.0),
                         event_log=EventLog(tmp_path / "ev.jsonl"),
                         sleep=lambda s: None)
        out = sup.run(timeout=5)
        assert not out.ok and out.attempts == 2 and out.restarts_used == 1
        kinds = [e["event"] for e in read_events(tmp_path / "ev.jsonl")]
        assert "budget_exhausted" in kinds

    def test_preemption_does_not_consume_budget(self):
        launcher = FakeLauncher([[_preempted()], [_preempted()], [_ok()]])
        sup = Supervisor(["prog"], 1, launcher=launcher,
                         policy=RestartPolicy(max_restarts=0),
                         sleep=lambda s: None)
        out = sup.run(timeout=5)
        assert out.ok and out.preemptions == 2 and out.restarts_used == 0

    def test_preemption_with_gang_killed_peers_counts_as_preemption(self):
        rows = [
            _preempted(0),
            WorkerResult(index=1, ok=False,
                         error="killed after peer failure (gang semantics)"),
        ]
        launcher = FakeLauncher([rows, [_ok(0), _ok(1)]])
        sup = Supervisor(["prog"], 2, launcher=launcher,
                         policy=RestartPolicy(max_restarts=0),
                         sleep=lambda s: None)
        out = sup.run(timeout=5)
        assert out.ok and out.preemptions == 1 and out.restarts_used == 0

    def test_preemption_with_error_none_peers_counts_as_preemption(self):
        """REGRESSION (ISSUE 7 satellite): gang-killed peer rows can
        surface with error=None (a launcher that reports disposition
        structurally, or an exit-code-only integration); the old
        '"peer failure" in error' string match classified the clean
        preemption as a budget-burning failure."""
        rows = [
            _preempted(0),
            WorkerResult(index=1, ok=False, error=None, exit_code=None),
        ]
        launcher = FakeLauncher([rows, [_ok(0), _ok(1)]])
        sup = Supervisor(["prog"], 2, launcher=launcher,
                         policy=RestartPolicy(max_restarts=0),
                         sleep=lambda s: None)
        out = sup.run(timeout=5)
        assert out.ok and out.preemptions == 1 and out.restarts_used == 0

    def test_independent_fault_next_to_preemption_still_burns_budget(self):
        """The flip side of the disposition fix: a peer that EXITED on its
        own (it has an exit code) during a preemption is an independent
        fault — the attempt must NOT classify as preemption."""
        rows = [
            _preempted(0),
            WorkerResult(index=1, ok=False, error="exit code 17",
                         exit_code=17, disposition="exited"),
        ]
        launcher = FakeLauncher([rows, [_ok(0), _ok(1)]])
        sup = Supervisor(["prog"], 2, launcher=launcher,
                         policy=RestartPolicy(max_restarts=1, backoff=0.0),
                         sleep=lambda s: None)
        out = sup.run(timeout=5)
        assert out.ok and out.preemptions == 0 and out.restarts_used == 1

    def test_events_carry_world_size_and_result_carries_resizes(
            self, tmp_path):
        """ISSUE 7 satellite: attempt_start/restart events name the
        attempt's world size and SupervisedResult surfaces resize
        accounting, so the JSONL log can attribute restarts to resizes."""
        launcher = FakeLauncher([[_fail()], [_ok()]])
        log = EventLog(tmp_path / "ev.jsonl")
        sup = Supervisor(["prog"], 1, launcher=launcher,
                         policy=RestartPolicy(max_restarts=1, backoff=0.0),
                         event_log=log, sleep=lambda s: None)
        out = sup.run(timeout=5)
        assert out.ok and out.resizes == 0 and out.world_size == 1
        events = log.read()
        assert all(e["world_size"] == 1 for e in events
                   if e["event"] in ("attempt_start", "attempt_end",
                                     "restart", "run_complete"))
        restart = next(e for e in events if e["event"] == "restart")
        assert restart["resizes"] == 0

    def test_preemption_cap_bounds_the_loop(self):
        launcher = FakeLauncher([[_preempted()]] * 3)
        sup = Supervisor(["prog"], 1, launcher=launcher,
                         policy=RestartPolicy(max_restarts=0,
                                              max_preemptions=2),
                         sleep=lambda s: None)
        out = sup.run(timeout=5)
        assert not out.ok and out.preemptions == 2 and out.attempts == 3

    def test_launcher_exception_becomes_failed_rows(self):
        launcher = FakeLauncher(["raise", [_ok()]])
        sup = Supervisor(["prog"], 1, launcher=launcher,
                         policy=RestartPolicy(max_restarts=1, backoff=0.0),
                         sleep=lambda s: None)
        out = sup.run(timeout=5)
        assert out.ok and out.restarts_used == 1


# ----------------------------------------------------- graceful mid-epoch ---
class TestGracefulStop:
    def test_stop_training_breaks_mid_epoch(self):
        model = _small_model()
        x, y = _data()
        stop = LambdaCallback(
            on_batch_end=lambda m, s, logs: (
                setattr(m, "stop_training", True) if s == 2 else None
            )
        )
        hist = model.fit(x, y, batch_size=32, epochs=3, steps_per_epoch=4,
                         verbose=0, callbacks=[stop])
        assert model.step == 2  # stopped at the batch boundary, not epoch
        assert len(hist.history["loss"]) == 1
        assert np.isfinite(hist.history["loss"][0])  # mean over 2 real steps


# ------------------------------------------------------------- preemption ---
class TestPreemptionHandler:
    def test_sigterm_checkpoints_and_stops_in_process(self, tmp_path):
        x, y = _data()
        kw = dict(batch_size=32, epochs=2, steps_per_epoch=4, verbose=0,
                  seed=7)

        preempt_at = 5
        send = LambdaCallback(
            on_batch_end=lambda m, s, logs: (
                os.kill(os.getpid(), signal.SIGTERM) if s == preempt_at
                else None
            )
        )
        handler = PreemptionHandler(tmp_path, exit_code=None)
        m2 = _small_model()
        m2.fit(x, y, **kw, callbacks=[send, handler])
        assert handler.triggered
        assert m2.step == preempt_at  # stopped right at the boundary
        assert dtpu.Checkpointer(tmp_path).latest_step() == preempt_at
        marker = read_resume_marker(tmp_path)
        assert marker and marker["step"] == preempt_at
        # Handler restored the previous SIGTERM disposition on train end.
        assert signal.getsignal(signal.SIGTERM) is signal.SIG_DFL

        # Relaunch of the identical command resumes and matches an
        # uninterrupted run exactly (the resume contract).
        m1 = _small_model()
        m1.fit(x, y, **kw)
        m3 = _small_model()
        m3.fit(x, y, **kw,
               callbacks=[ModelCheckpoint(tmp_path, save_freq=100,
                                          restore=True)])
        assert m3.step == m1.step
        import jax

        for a, b in zip(jax.tree_util.tree_leaves(m1.params),
                        jax.tree_util.tree_leaves(m3.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------- callbacks satellites --
class TestCallbackSatellites:
    def test_csvlogger_rows_durable_before_close(self, tmp_path):
        from distributed_tpu.training.callbacks import CSVLogger

        path = tmp_path / "log.csv"
        cb = CSVLogger(path)
        stub = types.SimpleNamespace()
        cb.on_epoch_end(stub, 0, {"loss": 1.5, "accuracy": 0.5})
        # Crash-visible: the row is on disk NOW, no close/flush needed.
        assert path.read_text() == "epoch,accuracy,loss\n0,0.5,1.5\n"
        cb.on_epoch_end(stub, 1, {"loss": 1.0, "accuracy": 0.75})
        assert path.read_text().splitlines()[-1] == "1,0.75,1.0"

    def test_sync_check_emits_event_and_raises(self, monkeypatch, tmp_path):
        from distributed_tpu.training.callbacks import SyncCheck
        from distributed_tpu.utils import events as ev
        from distributed_tpu.utils import sync_check as sc

        monkeypatch.setenv(ev.ENV_VAR, str(tmp_path / "ev.jsonl"))

        def boom(tree, what="params", cross_host=True):
            raise AssertionError(f"Replica divergence in {what} at fake")

        monkeypatch.setattr(sc, "assert_replicas_identical", boom)
        model = types.SimpleNamespace(params={}, state={}, opt_state={},
                                      step=12)
        with pytest.raises(AssertionError, match="divergence"):
            SyncCheck(every=1).on_epoch_end(model, 0, {})
        events = read_events(tmp_path / "ev.jsonl")
        assert events and events[0]["event"] == "sync_check_failed"
        assert events[0]["step"] == 12


# ----------------------------------------------------------- end to end -----
WORKER_BODY = """
    import os, sys, signal
    sys.path.insert(0, {repo!r})
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import distributed_tpu as dtpu
    from distributed_tpu.launch import report_result
    from distributed_tpu.resilience import FaultInjector, PreemptionHandler
    from distributed_tpu.training.callbacks import (
        LambdaCallback, ModelCheckpoint)

    CKPT = os.environ["TEST_CKPT_DIR"]
    x, y = dtpu.data.synthetic_images(256, (28, 28), 10, 0)
    x = x[..., None].astype(np.float32) / 255.0
    m = dtpu.Model(dtpu.models.mnist_cnn())
    m.compile(optimizer=dtpu.optim.SGD(0.05), metrics=["accuracy"])
    cbs = [ModelCheckpoint(CKPT, save_freq=3, restore=True)]

    pre_step = int(os.environ.get("TEST_PREEMPT_STEP", "0"))
    pre_marker = os.environ.get("TEST_PREEMPT_MARKER", "")
    if pre_step:
        def send_sigterm(model, step, logs):
            if step == pre_step and not os.path.exists(pre_marker):
                open(pre_marker, "w").close()
                os.kill(os.getpid(), signal.SIGTERM)
        cbs.append(LambdaCallback(on_batch_end=send_sigterm))
        cbs.append(PreemptionHandler(CKPT))

    fault = FaultInjector.from_env()
    if fault is not None:
        cbs.append(fault)

    hist = m.fit(x, y.astype(np.int32), batch_size=64, epochs=2,
                 steps_per_epoch=4, verbose=0, seed=0, callbacks=cbs)
    leaf = np.asarray(jax.tree_util.tree_leaves(m.params)[0]).ravel()[:4]
    report_result({{"loss": hist.metrics["loss"][-1],
                   "acc": hist.metrics["accuracy"][-1],
                   "leaf": [float(v) for v in leaf]}})
    """


@pytest.fixture(scope="module")
def worker_script(tmp_path_factory):
    path = tmp_path_factory.mktemp("resil") / "worker.py"
    path.write_text(textwrap.dedent(WORKER_BODY.format(repo=REPO)))
    return str(path)


@pytest.fixture(scope="module")
def reference_value(worker_script, tmp_path_factory):
    """The uninterrupted run's final loss/params-leaf — computed once and
    shared by every parity assertion in this module."""
    ckpt = tmp_path_factory.mktemp("ckpt_ref")
    results = LocalLauncher(
        env_extra={"TEST_CKPT_DIR": str(ckpt)}
    ).run([sys.executable, worker_script], 1, timeout=300)
    assert results[0].ok, (results[0].error, results[0].log_tail[-600:])
    return results[0].value


def _assert_parity(value, reference):
    assert value["loss"] == pytest.approx(reference["loss"], rel=1e-6)
    np.testing.assert_allclose(value["leaf"], reference["leaf"], rtol=1e-6)


# @slow (tier-1 budget, PR 17): ~8s real-process kill/restart; the
# TestSupervisorUnit restart-policy tests stay in-tier, and the
# serve_service kill test drives a real-process kill with token-exact
# recovery every run.
@pytest.mark.slow
def test_supervisor_kill_restart_resume_parity(worker_script, reference_value,
                                               tmp_path):
    """ACCEPTANCE: fault-injected worker kill mid-epoch -> automatic
    supervisor restart -> checkpoint resume -> final params match an
    uninterrupted run (fp32 tolerance)."""
    log = EventLog(tmp_path / "events.jsonl")
    sup = Supervisor(
        [sys.executable, worker_script], 1,
        policy=RestartPolicy(max_restarts=2, backoff=0.05, backoff_max=0.1),
        checkpoint_dir=tmp_path / "ckpt",
        event_log=log,
        env_extra={
            "TEST_CKPT_DIR": str(tmp_path / "ckpt"),
            "DTPU_FAULT": "kill:at_step=5",  # mid-epoch-2 (4 steps/epoch)
            "DTPU_FAULT_MARKER": str(tmp_path / "fault_once"),
        },
    )
    out = sup.run(timeout=300, grace=5)
    assert out.ok, [(r.index, r.error, r.log_tail[-600:]) for r in out.results]
    assert out.attempts == 2 and out.restarts_used == 1
    _assert_parity(out.results[0].value, reference_value)

    kinds = [e["event"] for e in log.read()]
    assert "fault_injected" in kinds  # worker-side event, shared log
    assert "restart" in kinds and kinds[-1] == "run_complete"
    restart = next(e for e in log.read() if e["event"] == "restart")
    assert restart["reason"] == "failure"
    assert restart["resume_step"] == 3  # latest complete ckpt before step 5


@pytest.mark.slow
def test_supervisor_preemption_restart_is_budget_free(worker_script,
                                                      reference_value,
                                                      tmp_path):
    """SIGTERM mid-epoch -> PreemptionHandler checkpoints step 5 + exits 75
    -> supervisor restarts WITHOUT spending the failure budget -> resumed
    run matches the uninterrupted one."""
    log = EventLog(tmp_path / "events.jsonl")
    sup = Supervisor(
        [sys.executable, worker_script], 1,
        policy=RestartPolicy(max_restarts=0, backoff=0.05),  # zero budget!
        checkpoint_dir=tmp_path / "ckpt",
        event_log=log,
        env_extra={
            "TEST_CKPT_DIR": str(tmp_path / "ckpt"),
            "TEST_PREEMPT_STEP": "5",
            "TEST_PREEMPT_MARKER": str(tmp_path / "preempted_once"),
        },
    )
    out = sup.run(timeout=300, grace=5)
    assert out.ok, [(r.index, r.error, r.log_tail[-600:]) for r in out.results]
    assert out.preemptions == 1 and out.restarts_used == 0
    # Params match the uninterrupted run exactly; the final-epoch LOSS
    # legitimately differs — the preemption checkpointed mid-epoch (step 5),
    # so the resumed final epoch averages its metrics over the 3 replayed
    # steps, not 4 (the "modulo the replayed partial epoch" caveat).
    np.testing.assert_allclose(out.results[0].value["leaf"],
                               reference_value["leaf"], rtol=1e-6)
    kinds = [e["event"] for e in log.read()]
    assert "preempted" in kinds  # worker-side PreemptionHandler event
    restart = next(e for e in log.read() if e["event"] == "restart")
    assert restart["reason"] == "preempted"
    assert restart["marker_step"] == 5  # resume marker from the handler
    # Run completed: the supervisor cleared the resume marker.
    assert read_resume_marker(tmp_path / "ckpt") is None


@pytest.mark.slow
@pytest.mark.parametrize("mode,fault,needs_liveness", [
    ("hang", "hang:at_step=5", True),
    ("slow_heartbeat", "slow_heartbeat:at_step=5,hang_seconds=10000", True),
    ("corrupt", "corrupt_checkpoint:at_step=6,directory={ckpt}", False),
])
def test_fault_matrix_restart_resume_parity(worker_script, reference_value,
                                            tmp_path, mode, fault,
                                            needs_liveness):
    """The rest of the fault matrix: hang (SIGSTOP — only the heartbeat
    probe can see it), slow-heartbeat (alive but stalled in Python), and
    corrupt-checkpoint (newest file clobbered after the step-6 save; the
    relaunch must fall back to step 3 and still reach parity)."""
    ckpt = tmp_path / "ckpt"
    log = EventLog(tmp_path / "events.jsonl")
    sup = Supervisor(
        [sys.executable, worker_script], 1,
        policy=RestartPolicy(max_restarts=2, backoff=0.05, backoff_max=0.1),
        checkpoint_dir=ckpt,
        event_log=log,
        liveness_timeout=3.0 if needs_liveness else None,
        env_extra={
            "TEST_CKPT_DIR": str(ckpt),
            "DTPU_FAULT": fault.format(ckpt=ckpt),
            "DTPU_FAULT_MARKER": str(tmp_path / "fault_once"),
        },
    )
    out = sup.run(timeout=300, grace=5)
    assert out.ok, [(r.index, r.error, r.log_tail[-600:]) for r in out.results]
    assert out.restarts_used == 1
    _assert_parity(out.results[0].value, reference_value)
    events = log.read()
    kinds = [e["event"] for e in events]
    if needs_liveness:
        # The first attempt must have died by liveness, not run timeout.
        end = next(e for e in events if e["event"] == "attempt_end")
        assert end["duration"] < 120
    else:
        assert "corrupt_checkpoint_skipped" in kinds
        restart = next(e for e in events if e["event"] == "restart")
        assert restart["resume_step"] == 3  # step-6 file is corrupt


def test_cli_supervise_end_to_end(tmp_path):
    """dtpu-launch --supervise: fail-once worker is restarted by the
    Supervisor and the run completes with rc 0 + event log."""
    import subprocess

    marker = tmp_path / "failed_once"
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(
        f"""
        import json, os, sys
        marker = {str(marker)!r}
        if not os.path.exists(marker):
            open(marker, "w").close()
            sys.exit(3)
        # report through the launcher's result-file protocol directly
        # (no framework import: keeps the CLI smoke fast)
        with open(os.environ["DTPU_RESULT_FILE"], "w") as f:
            json.dump({{"value": {{"attempt": os.environ["DTPU_ATTEMPT"]}}}}, f)
        """
    ))
    out_json = tmp_path / "rows.json"
    ev = tmp_path / "events.jsonl"
    proc = subprocess.run(
        [sys.executable, "-m", "distributed_tpu.launch", "--supervise",
         "--num-workers", "1", "--max-restarts", "2",
         "--event-log", str(ev), "--results-json", str(out_json),
         str(script)],
        capture_output=True, text=True, cwd=REPO, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert marker.exists()
    rows = json.loads(out_json.read_text())
    assert rows[0]["ok"] and rows[0]["value"] == {"attempt": "2"}
    kinds = [e["event"] for e in read_events(ev)]
    assert "restart" in kinds and kinds[-1] == "run_complete"
    assert "supervisor: attempts=2 restarts=1" in proc.stdout


@pytest.mark.slow
def test_bench_resilience_smoke():
    sys.path.insert(0, REPO)
    import bench

    out = bench.bench_resilience(throttled_calls=2000, beats=200,
                                 train_steps=6, kill_step=3)
    assert out["ok"] and out["attempts"] == 2
    assert out["value"] is not None and out["value"] > 0
    assert out["heartbeat_throttled_ns_per_call"] > 0
    assert out["heartbeat_beat_ns_per_call"] > 0
