"""ResNet family: residual composition, shapes, param counts, training.

The reference has no ResNet; these tests cover the scale-out model target
(BASELINE.json configs[3], SURVEY.md §7 build-order step 8) and the Residual
composition primitive the family is built from.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import distributed_tpu as dtpu
from distributed_tpu import nn


class TestResidual:
    def test_identity_shortcut(self):
        main = nn.Sequential([nn.Lambda(lambda x: 2.0 * x)])
        block = nn.Residual(main)
        params, state, out = block.init(jax.random.PRNGKey(0), (4,))
        assert out == (4,)
        x = jnp.arange(8.0).reshape(2, 4)
        y, _ = block.apply(params, state, x)
        np.testing.assert_allclose(y, 3.0 * x)

    def test_activation_applied_after_add(self):
        main = nn.Sequential([nn.Lambda(lambda x: -2.0 * x)])
        block = nn.Residual(main, activation="relu")
        params, state, _ = block.init(jax.random.PRNGKey(0), (3,))
        x = jnp.ones((2, 3))
        y, _ = block.apply(params, state, x)
        np.testing.assert_allclose(y, 0.0)  # relu(x - 2x) = relu(-x) = 0

    def test_shape_mismatch_raises(self):
        main = nn.Sequential([nn.Dense(7)])
        with pytest.raises(ValueError, match="projection"):
            nn.Residual(main).init(jax.random.PRNGKey(0), (4,))

    def test_projection_shortcut(self):
        main = nn.Sequential([nn.Dense(7)])
        block = nn.Residual(main, shortcut=nn.Sequential([nn.Dense(7)]))
        params, state, out = block.init(jax.random.PRNGKey(0), (4,))
        assert out == (7,)
        assert "shortcut" in params
        y, _ = block.apply(params, state, jnp.ones((2, 4)))
        assert y.shape == (2, 7)

    def test_batchnorm_state_threads_through(self):
        main = nn.Sequential([nn.Dense(4), nn.BatchNorm()])
        block = nn.Residual(main)
        params, state, _ = block.init(jax.random.PRNGKey(0), (4,))
        x = jnp.ones((8, 4))
        _, new_state = block.apply(params, state, x, train=True)
        assert "main" in new_state  # BN running stats propagate out

    def test_nested_dropout_gets_rng(self):
        # Regression: containers must report needs_rng for nested children.
        inner = nn.Sequential([nn.Dense(4), nn.Dropout(0.5)])
        outer = nn.Sequential([inner, nn.Dense(2)])
        assert outer.needs_rng
        params, state, _ = outer.init(jax.random.PRNGKey(0), (4,))
        y, _ = outer.apply(
            params, state, jnp.ones((2, 4)), train=True,
            rng=jax.random.PRNGKey(1),
        )
        assert y.shape == (2, 2)

    def test_residual_dropout_gets_rng(self):
        main = nn.Sequential([nn.Dense(4), nn.Dropout(0.5)])
        block = nn.Residual(main)
        assert block.needs_rng
        params, state, _ = block.init(jax.random.PRNGKey(0), (4,))
        y, _ = block.apply(
            params, state, jnp.ones((2, 4)), train=True,
            rng=jax.random.PRNGKey(1),
        )
        assert y.shape == (2, 4)


class TestResNet:
    # @slow (tier-1 budget, PR 17): ~7s resnet50-scale host init; the
    # block/shortcut wiring units and test_resnet18_param_count stay
    # in-tier pinning the same constructor math at a cheaper scale, and
    # `python bench.py resnet` builds the full resnet50.
    @pytest.mark.slow
    def test_resnet50_param_count(self):
        # Published torchvision/keras ResNet-50 v1.5 count.
        module = dtpu.models.resnet50(num_classes=1000)
        params, _, out = module.init(jax.random.PRNGKey(0), (224, 224, 3))
        assert out == (1000,)
        from distributed_tpu.utils.tree import tree_size

        assert tree_size(params) == 25_557_032

    def test_resnet18_param_count(self):
        module = dtpu.models.resnet18(num_classes=1000)
        params, _, _ = module.init(jax.random.PRNGKey(0), (224, 224, 3))
        from distributed_tpu.utils.tree import tree_size

        assert tree_size(params) == 11_689_512

    def test_small_inputs_forward(self):
        module = dtpu.models.resnet18(num_classes=10, small_inputs=True)
        params, state, out = module.init(jax.random.PRNGKey(0), (32, 32, 3))
        assert out == (10,)
        x = jnp.zeros((2, 32, 32, 3))
        logits, _ = module.apply(params, state, x, train=False)
        assert logits.shape == (2, 10)

    # @slow (tier-1 budget, PR 17): ~12s conv-stack DP training drive; the
    # architecture stays pinned in-tier (apply-shape + resnet50 param
    # count) and DP training numerics are covered in-tier by the
    # mnist_cnn strategy suite; `python bench.py resnet` drives training.
    @pytest.mark.slow
    def test_tiny_resnet_trains_dp(self, devices):
        # 1-block-per-stage bottleneck net on the 8-device mesh: the full
        # fit path (BN state, residual params, DP sharding) in one test.
        mesh = dtpu.make_mesh({"data": 8}, devices=devices)
        strategy = dtpu.DataParallel(mesh=mesh)
        with strategy.scope():
            model = dtpu.Model(
                dtpu.models.resnet(
                    50, num_classes=4, small_inputs=True,
                    stage_blocks=(1, 1, 1, 1), width=16,
                )
            )
            model.compile(
                optimizer=dtpu.optim.SGD(0.05, momentum=0.9),
                loss="sparse_categorical_crossentropy",
                metrics=["accuracy"],
            )
        x, y = dtpu.data.synthetic_images(256, (16, 16, 3), 4, seed=7)
        x = x.astype(np.float32) / 255.0
        hist = model.fit(x, y, batch_size=64, epochs=3, verbose=0, seed=0)
        assert hist.history["loss"][-1] < hist.history["loss"][0]
        # Replicas stay synchronized (the reference's key invariant,
        # /root/reference/README.md:226-232).
        for leaf in jax.tree_util.tree_leaves(model.params):
            shards = [np.asarray(s.data) for s in leaf.addressable_shards]
            for s in shards[1:]:
                np.testing.assert_array_equal(shards[0], s)

    def test_bf16_forward(self):
        module = dtpu.models.resnet18(
            num_classes=10, small_inputs=True, dtype=jnp.bfloat16
        )
        params, state, _ = module.init(jax.random.PRNGKey(0), (32, 32, 3))
        logits, _ = module.apply(
            params, state, jnp.zeros((2, 32, 32, 3)), train=False
        )
        assert logits.shape == (2, 10)


class TestImagenetLoader:
    def test_synthetic_imagenet(self):
        x, y = dtpu.data.load_imagenet(
            "train", image_size=64, synthetic_train_n=64, num_classes=1000
        )
        assert x.shape == (64, 64, 64, 3) and x.dtype == np.float32
        assert y.dtype == np.int32 and y.max() >= 256  # labels beyond uint8
