"""Execute the R binding's call surface through simulated reticulate
marshaling (VERDICT round 1, item 3).

No R interpreter exists in this image, so `tests/reticulate_sim.py`
transliterates every exported function in r/distributedtpu/R/*.R and drives
the real Python package through reticulate's R<->Python conversion rules
(doubles->float, integer vectors->int32, named lists->dicts, NULL->None,
float32 arrays round-tripping as float64, ...).

Covered flows mirror the reference end to end:
- local train (reference README.md:45-76)
- scoped distributed build + fit (README.md:118-154)
- TF_CONFIG-shaped cluster specs incl. the Spark-barrier port rewrite
  (README.md:84-89, 180-183)
- HDF5 save/retrieve (README.md:236-247)
- a real 2-process gang running the R-marshaled flow, asserting the
  replicas-identical invariant (README.md:226-232)

The final test asserts the harness covers 100% of the `dtpu()$...` call
sites extracted from the R sources — the VERDICT's done-criterion.
"""

import json
import re
import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent))

from reticulate_sim import (  # noqa: E402
    NULL,
    RArray,
    RList,
    RProxy,
    RVector,
    RBinding,
    r_c,
    r_character,
    r_double,
    r_int,
    r_logical,
    unlist,
)

R_SRC_DIR = Path(__file__).resolve().parents[1] / "r" / "distributedtpu" / "R"

# Module-level binding shared across tests; the coverage test (defined last,
# pytest runs file order) checks the union of recorded chains.
RB = RBinding()


@pytest.fixture
def rb():
    return RB


def _fit_small(rb, model, x, y, **kw):
    return rb.fit(model, x, y, batch_size=r_int(64), epochs=r_int(1),
                  steps_per_epoch=r_int(5), verbose=r_int(0), **kw)


def test_version_check(rb):
    """tf_version() parity (reference README.md:40-41)."""
    import distributed_tpu

    v = rb.dtpu_version()
    assert isinstance(v, RVector) and v.kind == "character"
    assert v.values == [distributed_tpu.__version__]


# @slow (tier-1 budget, PR 16): ~9s full train through reticulate; the R
# local train flow stays in tier-1 via test_r_execution.py's
# test_local_example_executes_and_trains, and the readme marshaling
# pieces via test_evaluate_and_predict_marshaling below.
@pytest.mark.slow
def test_local_flow_reference_readme_45_76(rb):
    """The reference's local R trainer, through R marshaling end to end."""
    d = rb.dataset_mnist()  # normalize=TRUE folds in the /255 of README.md:56
    train = d.get("train")
    x, y = train.get("x"), train.get("y")
    # reticulate delivered these as R arrays: doubles, even for labels.
    assert isinstance(x, RArray) and x.kind == "double"
    assert isinstance(y, RArray)
    assert x.array.ndim == 4 and x.array.shape[1:] == (28, 28, 1)
    assert float(x.array.max()) <= 1.0 + 1e-9

    model = rb.dtpu_model(rb.mnist_cnn(r_int(10)))
    rb.compile(model, optimizer=r_character("sgd"),
               learning_rate=r_double(0.05),
               loss=r_character("sparse_categorical_crossentropy"),
               metrics=r_c(r_character("accuracy")))
    h = _fit_small(rb, model, x, y)
    metrics = h.get("metrics")
    loss = metrics.get("loss")
    acc = metrics.get("accuracy")
    # result$metrics$accuracy must be a plain numeric vector (the value the
    # reference's Spark closure reads, README.md:220) — proxies leaking here
    # would break max()/as.character() on the R side.
    assert isinstance(loss, RVector) and loss.kind == "double"
    assert isinstance(acc, RVector) and acc.kind == "double"
    assert len(loss) == 1 and np.isfinite(loss.values[0])
    assert 0.0 <= acc.values[0] <= 1.0


# @slow (tier-1 budget, PR 17): ~16s R-bridge drive; evaluate/predict
# semantics are first-class jax-side (test_transformer, test_generate),
# and the R marshal layer stays canaried in-tier by the weights-
# roundtrip and scoped-distributed-build tests below.
@pytest.mark.slow
def test_evaluate_and_predict_marshaling(rb):
    d = rb.dataset_mnist()
    train = d.get("train")
    x, y = train.get("x"), train.get("y")
    model = rb.dtpu_model(rb.mnist_cnn())
    rb.compile(model, learning_rate=r_double(0.05))
    _fit_small(rb, model, x, y)

    xs = RArray(x.array[:64], "double")
    ys = RArray(y.array[:64], y.kind)
    res = rb.evaluate(model, xs, ys, batch_size=r_int(32))
    assert isinstance(res, RList) and "loss" in res.names
    for item in res.items:
        assert isinstance(item, RVector) and item.kind == "double"

    preds = rb.predict_on_batch(model, xs, batch_size=r_int(32))
    # float32 logits arrive in R as a double array.
    assert isinstance(preds, RArray) and preds.kind == "double"
    assert preds.array.shape == (64, 10)

    rb.summary_model(model)


# @slow (tier-1 budget, PR 17): ~5s R-bridge drive; validation_data
# handling is covered jax-side in the fit/callbacks suites and the
# R-list marshal path by the in-tier weights-roundtrip test.
@pytest.mark.slow
def test_validation_data_as_r_list(rb):
    """fit(validation_data = list(x, y)) — an unnamed R list crossing as a
    Python [x, y] list (the README's val-metrics surface)."""
    d = rb.dataset_mnist()
    train = d.get("train")
    x, y = train.get("x"), train.get("y")
    val = RList([RArray(x.array[:64], "double"), RArray(y.array[:64], y.kind)])
    model = rb.dtpu_model(rb.mnist_cnn())
    rb.compile(model, learning_rate=r_double(0.05))
    h = _fit_small(rb, model, x, y, validation_data=val)
    metrics = h.get("metrics")
    assert "val_loss" in metrics.names
    assert metrics.get("val_loss").kind == "double"


def test_scoped_distributed_build_readme_118_154(rb):
    """strategy + with(strategy$scope(), {build}) + global-batch fit."""
    strategy = rb.multi_worker_mirrored_strategy()
    n = rb.num_replicas_in_sync(strategy)
    assert isinstance(n, RVector) and n.kind == "integer"
    num_replicas = n.values[0]
    assert num_replicas == 8  # the CPU sim mesh

    d = rb.dataset_mnist()
    train = d.get("train")
    x, y = train.get("x"), train.get("y")

    built = {}

    def build_model():
        m = rb.dtpu_model(rb.mnist_cnn())
        rb.compile(m, learning_rate=r_double(0.05))
        built["m"] = m
        return m

    rb.with_strategy_scope(strategy, build_model)
    # global batch = per-worker 64 x replicas (README.md:124-125)
    gb = 8 * num_replicas
    h = rb.fit(built["m"], x, y, batch_size=r_int(gb), epochs=r_int(1),
               steps_per_epoch=r_int(3), verbose=r_int(0))
    assert len(h.get("metrics").get("loss")) == 1

    # Also exercise the two plain strategy constructors the R API exports.
    assert rb.num_replicas_in_sync(rb.single_device_strategy()).values[0] == 1
    assert rb.num_replicas_in_sync(rb.data_parallel_strategy()).values[0] == 8


def test_cluster_spec_schema_readme_84_89(rb, monkeypatch):
    monkeypatch.delenv("DTPU_CONFIG", raising=False)
    workers = r_c(
        r_character("10.0.0.1:10087"), r_character("10.0.0.2:10088"),
        r_character("10.0.0.3:10089"), r_character("10.0.0.4:10090"),
    )
    spec_json = rb.set_cluster_spec(workers, r_int(2))
    spec = json.loads(spec_json)
    # Exact reference schema (README.md:84-89), auto_unbox semantics:
    # scalars unboxed, the worker list stays a list.
    assert spec == {
        "cluster": {"worker": ["10.0.0.1:10087", "10.0.0.2:10088",
                               "10.0.0.3:10089", "10.0.0.4:10090"]},
        "task": {"type": "worker", "index": 2},
    }
    from distributed_tpu.cluster import from_env

    parsed = from_env()
    assert parsed.index == 2
    assert parsed.num_processes == 4
    assert parsed.workers[0] == "10.0.0.1:10087"


def test_single_worker_spec_stays_listy(rb, monkeypatch):
    """jsonlite auto_unbox would collapse a length-1 worker vector to a JSON
    scalar — the as.list() in strategy.R:43 prevents it. Pin that."""
    monkeypatch.delenv("DTPU_CONFIG", raising=False)
    spec = json.loads(rb.set_cluster_spec(r_character("h:1"), r_int(0)))
    assert spec["cluster"]["worker"] == ["h:1"]


def test_barrier_cluster_spec_readme_180_183(rb, monkeypatch):
    """Spark's ports stripped, 8000+seq_along(hosts) (1-based!) assigned."""
    monkeypatch.delenv("DTPU_CONFIG", raising=False)
    addresses = r_c(r_character("10.1.1.1:34567"),
                    r_character("10.1.1.2:34568"),
                    r_character("10.1.1.3:34569"))
    rb.barrier_cluster_spec(addresses, r_int(1))
    spec = json.loads(__import__("os").environ["DTPU_CONFIG"])
    assert spec["cluster"]["worker"] == [
        "10.1.1.1:8001", "10.1.1.2:8002", "10.1.1.3:8003"
    ]
    assert spec["task"]["index"] == 1


# @slow (tier-1 budget, PR 17): ~7s R-bridge drive; the hdf5 roundtrip
# itself is covered jax-side (test_export) and R-side persistence stays
# canaried in-tier by test_weights_save_load_roundtrip_from_r.
@pytest.mark.slow
def test_hdf5_save_load_roundtrip_readme_236_247(rb, tmp_path):
    """save_model_hdf5 / load_model_hdf5 through R marshaling: float32
    params come back to R as float64 and must load back losslessly (JAX
    casts to the weak dtype on placement)."""
    d = rb.dataset_mnist()
    train = d.get("train")
    x, y = train.get("x"), train.get("y")
    model = rb.dtpu_model(rb.mnist_cnn())
    rb.compile(model, learning_rate=r_double(0.05))
    _fit_small(rb, model, x, y)

    path = str(tmp_path / "model.hdf5")
    rb.save_model_hdf5(model, r_character(path))

    xs = RArray(x.array[:32], "double")
    before = rb.predict_on_batch(model, xs).array

    model2 = rb.dtpu_model(rb.mnist_cnn())
    rb.compile(model2, learning_rate=r_double(0.05))
    # load_model_hdf5 requires a built model (model.R:116).
    model2._obj.build((28, 28, 1))
    rb.load_model_hdf5(model2, r_character(path))
    after = rb.predict_on_batch(model2, xs).array
    np.testing.assert_allclose(before, after, atol=1e-5)


def test_callbacks_constructed_from_r(rb, tmp_path):
    d = rb.dataset_mnist()
    train = d.get("train")
    x, y = train.get("x"), train.get("y")
    model = rb.dtpu_model(rb.mnist_cnn())
    rb.compile(model, learning_rate=r_double(0.05))

    ckpt_dir = str(tmp_path / "ckpts")
    csv_path = str(tmp_path / "log.csv")
    cbs = RList([
        rb.model_checkpoint_callback(r_character(ckpt_dir),
                                     save_freq=r_character("epoch"),
                                     keep=r_int(2)),
        rb.early_stopping_callback(monitor=r_character("loss"),
                                   patience=r_int(1)),
        rb.csv_logger_callback(r_character(csv_path)),
    ])
    h = rb.fit(model, x, y, batch_size=r_int(64), epochs=r_int(2),
               steps_per_epoch=r_int(2), verbose=r_int(0), callbacks=cbs)
    assert len(h.get("metrics").get("loss")) == 2
    assert Path(csv_path).exists()
    assert any(Path(ckpt_dir).iterdir())

    # numeric save_freq goes through the as.integer branch (model.R:130)
    cb = rb.model_checkpoint_callback(r_character(ckpt_dir),
                                      save_freq=r_double(5.0))
    assert cb._obj.save_freq == 5

    # LR callbacks: schedule fn applies through set_learning_rate; plateau
    # factor/patience marshal through as.numeric/as.integer; TensorBoard
    # writes chief-only event files.
    tb_dir = str(tmp_path / "tb")
    cbs2 = RList([
        rb.learning_rate_scheduler_callback(lambda epoch: 0.05 / (epoch + 1)),
        rb.reduce_lr_on_plateau_callback(monitor=r_character("loss"),
                                         factor=r_double(0.5),
                                         patience=r_int(2)),
        rb.tensorboard_callback(r_character(tb_dir)),
    ])
    rb.fit(model, x, y, batch_size=r_int(64), epochs=r_int(2),
           steps_per_epoch=r_int(2), verbose=r_int(0), callbacks=cbs2)
    assert abs(model._obj.get_learning_rate() - 0.025) < 1e-9
    assert any("tfevents" in p.name for p in Path(tb_dir).iterdir())


def test_resnet_and_cifar_constructors(rb):
    """The other two model constructors model.R exports; logical and integer
    marshaling on their arguments."""
    m = rb.dtpu_model(rb.resnet50(num_classes=r_int(10),
                                  small_inputs=r_logical(True)))
    rb.compile(m, learning_rate=r_double(0.1))
    m._obj.build((32, 32, 3))
    assert m._obj.num_params > 0

    c = rb.dtpu_model(rb.cifar_cnn(r_int(10)))
    rb.compile(c)
    c._obj.build((32, 32, 3))


@pytest.mark.slow
def test_other_dataset_loaders(rb):
    # @slow (tier-1 budget triage, the PR 6 whale precedent): 18s of
    # dataset synthesis to check two loaders whose Python side is covered
    # by test_datasets.py and whose R marshaling is exercised by the
    # mnist loader tests above.
    for d in (rb.dataset_fashion_mnist(), rb.dataset_cifar10()):
        x = d.get("train").get("x")
        assert isinstance(x, RArray) and x.array.ndim == 4


@pytest.mark.slow
def test_distributed_2proc_r_flow(tmp_path):
    """The reference's Spark-barrier distributed run (README.md:170-232),
    R-marshaled: 2 gang processes each build the cluster spec via
    barrier_cluster_spec, train under the mirrored strategy, and return
    max(result$metrics$accuracy) as.character — identical on every worker
    (README.md:226-232)."""
    import textwrap

    from distributed_tpu.launch import LocalLauncher

    repo = str(Path(__file__).resolve().parents[1])
    tests_dir = str(Path(__file__).resolve().parent)
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(f"""
        import os, sys
        sys.path.insert(0, {repo!r})
        sys.path.insert(0, {tests_dir!r})
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        import jax
        jax.config.update("jax_platforms", "cpu")

        # The launcher injects DTPU_CONFIG; re-derive barrier-style inputs
        # from it, then rebuild the spec the R way (barrier$address carries
        # Spark ports that must be stripped and re-assigned). Use the
        # original ports as the base so the rewritten spec still points at
        # the live gang.
        import json
        env_spec = json.loads(os.environ["DTPU_CONFIG"])
        peers = env_spec["cluster"]["worker"]
        rank = env_spec["task"]["index"]
        ports = [int(p.rsplit(":", 1)[1]) for p in peers]

        from reticulate_sim import (RBinding, RList, r_character, r_c,
                                    r_int, r_double)
        rb = RBinding()
        addresses = r_c(*[r_character(h.rsplit(":", 1)[0] + ":34567")
                          for h in peers])
        # base_port chosen so 8000+seq lands on the real gang ports.
        rb.barrier_cluster_spec(addresses, r_int(rank),
                                base_port=r_int(ports[0] - 1))
        spec = json.loads(os.environ["DTPU_CONFIG"])
        assert spec["task"]["index"] == rank
        # seq_along must have preserved rank order of the original list
        expect = [p.rsplit(":", 1)[0] + ":" + str(ports[0] + i)
                  for i, p in enumerate(peers, start=1)]
        assert spec["cluster"]["worker"] == [
            p.rsplit(":", 1)[0] + ":" + str(ports[0] - 1 + i)
          for i, p in enumerate(peers, start=1)], spec

        # Port rewriting can't target the actual listener ports the
        # launcher opened, so restore the real spec for initialize() —
        # the schema round-trip above is the marshaling test.
        os.environ["DTPU_CONFIG"] = json.dumps(env_spec)

        import distributed_tpu as dtpu
        dtpu.cluster.initialize()

        d = rb.dataset_mnist()
        train = d.get("train")
        x, y = train.get("x"), train.get("y")

        built = {{}}
        def build():
            m = rb.dtpu_model(rb.mnist_cnn())
            rb.compile(m, learning_rate=r_double(0.05))
            built["m"] = m
        strategy = rb.multi_worker_mirrored_strategy()
        rb.with_strategy_scope(strategy, build)
        h = rb.fit(built["m"], x, y, batch_size=r_int(64), epochs=r_int(2),
                   steps_per_epoch=r_int(3), verbose=r_int(0))
        # as.character(max(result$metrics$accuracy)) (README.md:220)
        acc = max(h.get("metrics").get("accuracy").values)
        from distributed_tpu.launch import report_result
        report_result({{"rank": rank, "acc_chr": repr(acc)}})
        """))
    results = LocalLauncher().run([sys.executable, str(script)], 2,
                                  timeout=300)
    assert all(r.ok for r in results), [
        (r.index, r.error, r.log_tail[-800:]) for r in results
    ]
    accs = {r.value["acc_chr"] for r in results}
    assert len(accs) == 1  # replicas identical, README.md:226-232


def test_weights_save_load_roundtrip_from_r(rb, tmp_path):
    """save_model_weights_hdf5 / load_model_weights_hdf5: the Keras-named
    weight round-trip (params + state) driven through R marshaling."""
    d = rb.dataset_mnist()
    train = d.get("train")
    x, y = train.get("x"), train.get("y")
    model = rb.dtpu_model(rb.mnist_cnn())
    rb.compile(model, learning_rate=r_double(0.05))
    _fit_small(rb, model, x, y)

    path = str(tmp_path / "weights.hdf5")
    rb.save_model_weights_hdf5(model, r_character(path))

    xs = RArray(x.array[:32], "double")
    before = rb.predict_on_batch(model, xs).array

    model2 = rb.dtpu_model(rb.mnist_cnn())
    rb.compile(model2, learning_rate=r_double(0.05))
    model2._obj.build((28, 28, 1))
    rb.load_model_weights_hdf5(model2, r_character(path))
    after = rb.predict_on_batch(model2, xs).array
    np.testing.assert_allclose(before, after, atol=1e-5)


# -- keep last: coverage over every dtpu()$... call site --------------------


def test_chain_coverage_is_100_percent():
    """Every `dtpu()$<chain>` in r/distributedtpu/R/*.R was executed
    through the marshaling harness above (VERDICT #3 done-criterion)."""
    src = "\n".join(p.read_text() for p in sorted(R_SRC_DIR.glob("*.R")))
    chains = set(re.findall(r"dtpu\(\)\$(`?[A-Za-z_][A-Za-z_$0-9]*`?)", src))
    chains = {c.replace("`", "") for c in chains}
    assert chains, "no call sites found — extraction regex broke"
    recorded = RB._bridge.chains
    missing = {c for c in chains if c not in recorded}
    assert not missing, (
        f"R call sites never executed through the harness: {sorted(missing)};"
        f" executed: {sorted(recorded)}"
    )
