"""Ring attention + sequence parallelism (DataSeqParallel).

Long-context capability beyond the reference (which has no sequence
dimension, SURVEY.md §5): exactness of the ring online-softmax against dense
attention, gradients through the ring, and end-to-end training equivalence
under a data x seq mesh on the 8-device sim.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec

import distributed_tpu as dtpu
from distributed_tpu import nn
from distributed_tpu.ops.ring_attention import ring_attention


def _dense_reference(q, k, v, causal):
    qf, kf, vf = (a.astype(jnp.float32) for a in (q, k, v))
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) / jnp.sqrt(jnp.float32(d))
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, vf).astype(q.dtype)


def _qkv(b=2, t=16, h=2, d=8, seed=0):
    keys = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (b, t, h, d)) for k in keys)


class TestRingAttentionOp:
    # causal=True @slow (tier-1 budget, PR 16): the causal ring-vs-dense
    # parity stays in tier-1 via test_zigzag_matches_naive_and_dense
    # (causal, both schedules, width 8); the non-causal variant has no
    # other in-tier coverage and stays.
    @pytest.mark.parametrize("causal", [
        False,
        pytest.param(True, marks=pytest.mark.slow),
    ])
    def test_matches_dense(self, devices, causal):
        mesh = dtpu.make_mesh({"seq": 8}, devices=devices)
        q, k, v = _qkv()
        out = ring_attention(q, k, v, mesh=mesh, causal=causal)
        ref = _dense_reference(q, k, v, causal)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    # n_seq 2 and 4 @slow (tier-1 budget, PR 10): each ring width compiles
    # its own ~9s program and the property is identical; the widest ring
    # (8, the most schedule hops) stays in tier-1.
    @pytest.mark.parametrize("n_seq", [
        pytest.param(2, marks=pytest.mark.slow),
        pytest.param(4, marks=pytest.mark.slow),
        8,
    ])
    def test_zigzag_matches_naive_and_dense(self, devices, n_seq):
        """The balanced causal schedule is numerically a re-association of
        the same softmax — both schedules must match dense, for even AND
        odd chunk-pair counts."""
        mesh = dtpu.make_mesh({"seq": n_seq}, devices=devices[:n_seq])
        q, k, v = _qkv(t=16)
        ref = _dense_reference(q, k, v, True)
        for schedule in ("zigzag", "naive"):
            out = ring_attention(q, k, v, mesh=mesh, causal=True,
                                 schedule=schedule)
            np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5,
                                       err_msg=schedule)

    @pytest.mark.slow
    def test_zigzag_gradients_match_naive(self, devices):
        # @slow: differentiating the 4-hop ppermute ring compiles ~25s+ on
        # the 1-core tier-1 box; forward-path zigzag-vs-naive equivalence
        # (test_zigzag_matches_naive_and_dense) stays in tier-1.
        mesh = dtpu.make_mesh({"seq": 4}, devices=devices[:4])
        q, k, v = _qkv(t=16)

        def loss(schedule):
            def f(q, k, v):
                return jnp.sum(
                    ring_attention(q, k, v, mesh=mesh, causal=True,
                                   schedule=schedule) ** 2
                )
            return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

        for a, b in zip(loss("zigzag"), loss("naive")):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)

    def test_zigzag_requires_causal_and_even_shard(self, devices):
        mesh = dtpu.make_mesh({"seq": 8}, devices=devices)
        q, k, v = _qkv(t=16)
        with pytest.raises(ValueError, match="zigzag"):
            ring_attention(q, k, v, mesh=mesh, causal=False,
                           schedule="zigzag")
        q2, k2, v2 = _qkv(t=8)  # per-shard length 1: cannot split in half
        with pytest.raises(ValueError, match="zigzag"):
            ring_attention(q2, k2, v2, mesh=mesh, causal=True,
                           schedule="zigzag")
        # auto silently falls back to naive for the same inputs.
        out = ring_attention(q2, k2, v2, mesh=mesh, causal=True)
        np.testing.assert_allclose(
            out, _dense_reference(q2, k2, v2, True), rtol=1e-5, atol=1e-5
        )

    def test_data_x_seq_mesh(self, devices):
        mesh = dtpu.make_mesh({"data": 2, "seq": 4}, devices=devices)
        q, k, v = _qkv(b=4, t=32, seed=1)
        out = ring_attention(
            q, k, v, mesh=mesh, batch_axis="data", causal=True
        )
        ref = _dense_reference(q, k, v, True)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    def test_sharded_inputs_stay_sharded(self, devices):
        mesh = dtpu.make_mesh({"seq": 4}, devices=devices[:4])
        q, k, v = _qkv(t=32, seed=2)
        sh = NamedSharding(mesh, PartitionSpec(None, "seq", None, None))
        q, k, v = (jax.device_put(a, sh) for a in (q, k, v))
        out = jax.jit(
            lambda a, b, c: ring_attention(
                a, b, c, mesh=mesh, causal=True
            )
        )(q, k, v)
        assert out.sharding.spec == PartitionSpec(None, "seq", None, None)
        np.testing.assert_allclose(
            out, _dense_reference(q, k, v, True), rtol=1e-5, atol=1e-5
        )

    @pytest.mark.slow
    def test_gradients_match_dense(self, devices):
        # @slow: grad-of-ring compile is a tier-1 whale (see above); the
        # end-to-end LM training equivalence test below still runs grads
        # through the ring inside tier-1.
        mesh = dtpu.make_mesh({"seq": 4}, devices=devices[:4])
        q, k, v = _qkv(t=16, seed=3)

        def loss_ring(q, k, v):
            return jnp.sum(
                ring_attention(q, k, v, mesh=mesh, causal=True) ** 2
            )

        def loss_dense(q, k, v):
            return jnp.sum(_dense_reference(q, k, v, True) ** 2)

        gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gr, gd):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)

    def test_indivisible_seq_raises(self, devices):
        mesh = dtpu.make_mesh({"seq": 8}, devices=devices)
        q, k, v = _qkv(t=12)
        with pytest.raises(ValueError, match="divisible"):
            ring_attention(q, k, v, mesh=mesh)


class TestDataSeqParallel:
    def test_batch_sharding(self, devices):
        strategy = dtpu.DataSeqParallel(seq_parallel=4)
        batch = strategy.put_batch(
            {"x": np.zeros((8, 16), np.int32), "y": np.zeros((8,), np.int32)}
        )
        assert batch["x"].sharding.spec == PartitionSpec("data", "seq")
        assert batch["y"].sharding.spec == PartitionSpec("data")

    def test_seq_indivisible_raises(self, devices):
        strategy = dtpu.DataSeqParallel(seq_parallel=4)
        with pytest.raises(ValueError, match="divisible"):
            strategy.put_batch({"x": np.zeros((8, 18), np.int32)})

    # @slow (tier-1 budget, PR 17): ~13s data x seq LM training drive; the
    # data x seq mesh composition stays in-tier via test_data_x_seq_mesh
    # and TestDataSeq::test_equals_dataseqparallel (test_composite.py), and
    # ring-vs-dense numerics stay in-tier via the op-level parity tests.
    @pytest.mark.slow
    def test_lm_trains_and_matches_dense(self, devices):
        VOCAB = 32
        rng = np.random.default_rng(0)
        starts = rng.integers(0, VOCAB, size=64)
        toks = (starts[:, None] + np.arange(17)[None]) % VOCAB
        x = toks[:, :-1].astype(np.int32)
        y = toks[:, 1:].astype(np.int32)

        def train(strategy):
            def build():
                m = dtpu.Model(
                    dtpu.models.transformer_lm(
                        VOCAB, num_layers=1, d_model=32, num_heads=2,
                        max_len=16,
                    )
                )
                m.compile(optimizer=dtpu.optim.SGD(0.1),
                          loss="sparse_categorical_crossentropy")
                return m

            if strategy is None:
                model = build()
            else:
                with strategy.scope():
                    model = build()
            hist = model.fit(x, y, batch_size=32, epochs=2, verbose=0,
                             seed=4, shuffle=False)
            return hist.history["loss"]

        ref = train(None)
        sp = train(dtpu.DataSeqParallel(seq_parallel=4))
        np.testing.assert_allclose(ref, sp, rtol=2e-4, atol=2e-5)
