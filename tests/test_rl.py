"""Online post-training: rollout packing, the policy-gradient loss, the
baseline/KL state, and the closed loop end-to-end.

The serving-side halves (logprob capture, per-request RNG determinism,
the update_weights staleness contract) are pinned in test_serving.py;
here the focus is the trainer side and the loop that joins them. Kept
lean per the tier-1 budget: one module-scoped tiny LM + engine, every
PostTrainer test reuses the same compiled shapes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import distributed_tpu as dtpu
from distributed_tpu import optim, rl
from distributed_tpu.serving import Engine


@pytest.fixture(scope="module")
def lm():
    model = dtpu.Model(dtpu.models.transformer_lm(
        32, num_layers=1, d_model=16, num_heads=2, max_len=64))
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    model.build((32,))
    return model


@pytest.fixture(scope="module")
def sampling_engine(lm):
    """Shared across the loop tests: a fresh Engine pays its own
    prefill/decode compiles, and the loop's correctness never depends on
    which engine instance carries it (update_weights re-snapshots)."""
    return Engine(lm, max_slots=2, block_size=8, max_len=64,
                  temperature=1.0, seed=3)


def _prompts(n=4, size=4, vocab=32, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, vocab, (size,)).astype(np.int32)
            for _ in range(n)]


# ---------------------------------------------------------------- packing --
def test_pack_rollouts_alignment():
    """Targets shift by one, the mask selects exactly the positions whose
    TARGET is a completion token, and rollout logprobs land index-aligned
    with those positions."""
    r = rl.Rollout(
        tokens=np.array([7, 8, 9, 1, 2, 3], np.int64),  # prompt 3, gen 3
        prompt_len=3,
        logprobs=np.array([-0.5, -1.0, -1.5]),
        advantage=2.0,
    )
    x, y = rl.pack_rollouts([r], train_len=8, kl_coef=0.25)
    assert x.shape == (1, 7) and y.shape == (1, 7, 5)
    np.testing.assert_array_equal(x[0], [7, 8, 9, 1, 2, 0, 0])
    np.testing.assert_array_equal(y[0, :, 0], [8, 9, 1, 2, 3, 0, 0])
    np.testing.assert_array_equal(y[0, :, 3], [0, 0, 1, 1, 1, 0, 0])
    np.testing.assert_allclose(y[0, 2:5, 1], 2.0)  # advantage on mask
    np.testing.assert_allclose(y[0, 2:5, 2], [-0.5, -1.0, -1.5])
    assert np.all(y[0, :, 4] == 0.25)  # kl coef rides the batch
    with pytest.raises(ValueError, match="train_len"):
        rl.pack_rollouts([r], train_len=5)
    with pytest.raises(ValueError, match="logprobs"):
        rl.pack_rollouts(
            [rl.Rollout(r.tokens, 3, np.array([-0.5]))], train_len=8
        )


def test_rl_loss_gradient_direction():
    """REINFORCE sanity: with positive advantage the loss gradient must
    INCREASE the chosen token's logit relative to the rest; the KL term
    is zero on-policy and >= 0 off-policy (k3 estimator)."""
    loss = rl.rl_loss()
    logits = jnp.zeros((1, 2, 4))
    y = np.zeros((1, 2, 5), np.float32)
    y[0, 0] = [2, 1.0, float(np.log(0.25)), 1.0, 0.0]  # on-policy ref
    y = jnp.asarray(y)
    g = jax.grad(lambda l: loss(l, y))(logits)
    assert g[0, 0, 2] < 0  # push chosen logit UP (minimizing loss)
    assert np.all(np.asarray(g[0, 0, [0, 1, 3]]) > 0)
    assert np.allclose(g[0, 1], 0.0)  # masked position contributes nothing
    # KL term: on-policy (ref == current) contributes exactly 0, any
    # drift contributes positively.
    ykl = np.zeros((1, 2, 5), np.float32)
    ykl[0, 0] = [2, 0.0, float(np.log(0.25)), 1.0, 1.0]
    on = float(loss(logits, jnp.asarray(ykl)))
    assert abs(on) < 1e-6
    ykl[0, 0, 2] = float(np.log(0.5))  # reference more confident
    off = float(loss(logits, jnp.asarray(ykl)))
    assert off > 0


def test_rl_loss_ppo_clip_matches_reinforce_on_policy():
    """On-policy the clipped surrogate IS the ratio-1 REINFORCE direction
    (gradient magnitudes differ off-policy only when clipping engages)."""
    logits = jnp.asarray(np.random.default_rng(0).standard_normal((1, 3, 4)),
                         jnp.float32)
    lp = jax.nn.log_softmax(logits, -1)
    y = np.zeros((1, 3, 5), np.float32)
    for t in range(2):
        tok = t + 1
        y[0, t] = [tok, 1.5, float(lp[0, t, tok]), 1.0, 0.0]
    y = jnp.asarray(y)
    g_plain = jax.grad(lambda l: rl.rl_loss()(l, y))(logits)
    g_clip = jax.grad(lambda l: rl.rl_loss(ppo_clip=0.2)(l, y))(logits)
    np.testing.assert_allclose(np.asarray(g_plain), np.asarray(g_clip),
                               atol=1e-6)


# ---------------------------------------------------------- optim state --
def test_ema_baseline_and_adaptive_kl():
    b = optim.EmaBaseline(decay=0.5)
    assert b.value is None
    assert b.update(4.0) == 4.0  # cold start adopts the mean
    assert b.update(0.0) == 2.0
    s = b.state_dict()
    b2 = optim.EmaBaseline()
    b2.load_state(s)
    assert b2.value == 2.0 and b2.decay == 0.5
    with pytest.raises(ValueError):
        optim.EmaBaseline(decay=1.0)

    k = optim.AdaptiveKLCoef(init_coef=0.1, target=0.01, factor=2.0,
                             tolerance=1.5)
    assert k.update(0.10) == pytest.approx(0.2)   # overshoot: grow
    assert k.update(0.001) == pytest.approx(0.1)  # timid: shrink
    assert k.update(0.01) == pytest.approx(0.1)   # in band: hold
    k2 = optim.AdaptiveKLCoef()
    k2.load_state(k.state_dict())
    assert k2.coef == pytest.approx(0.1)


# ------------------------------------------------------------- the loop --
def test_post_trainer_requires_sampling_engine(lm):
    greedy = Engine(lm, max_slots=1, block_size=8, max_len=64)
    with pytest.raises(ValueError, match="temperature"):
        rl.PostTrainer(lm, greedy)


def test_post_trainer_closed_loop_improves_and_syncs(lm, sampling_engine):
    """The end-to-end gate at test scale: rewards improve from the first
    iteration to the last, every iteration hot-swaps (weights_version
    marches), the measured KL is finite and positive, and the engine
    really serves the trained weights (its snapshot equals the trainer's
    masters after sync)."""
    engine = sampling_engine
    pt = rl.PostTrainer(
        lm, engine, reward_fn=rl.length_penalized_logprob(0.0),
        learning_rate=1e-2, kl_coef=0.01, seed=0,
    )
    rows = pt.train(_prompts(4, seed=0), iterations=3, num_samples=4,
                    max_new_tokens=16, train_epochs=2)
    rewards = [r["reward_mean"] for r in rows]
    assert rewards[-1] > rewards[0], rewards
    assert [r["weights_version"] for r in rows] == [1, 2, 3]
    assert all(r["kl"] is not None and np.isfinite(r["kl"]) for r in rows)
    assert all(r["weight_sync_s"] >= 0 for r in rows)
    assert pt.baseline.value is not None
    # The engine's served snapshot IS the trainer's masters post-sync.
    for a, b in zip(jax.tree_util.tree_leaves(engine._params),
                    jax.tree_util.tree_leaves(lm.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # History rows carry the three loop couplings the bench prices.
    for key in ("rollout_tokens_per_sec", "train_steps_per_sec",
                "weight_sync_s"):
        assert rows[-1][key] > 0
    # An AdaptiveKLCoef plugs in where the float goes and is driven by
    # the measured post-update KL, with no recompile: the coef rides in
    # the packed batch (y channel 4), not the trace — same shapes, same
    # compiled step.
    ctl = optim.AdaptiveKLCoef(init_coef=0.05, target=1e-4, factor=2.0)
    pt.kl = ctl
    row = pt.iterate(_prompts(4, seed=0), num_samples=4,
                     max_new_tokens=16, train_epochs=2)
    # Any real update at lr 1e-2 overshoots a 1e-4 KL target: coef grew.
    assert ctl.coef == pytest.approx(0.1)
    assert row["kl_coef"] == pytest.approx(0.1)


@pytest.mark.slow
def test_post_trainer_composes_with_mesh_strategy_and_grad_accum():
    """The heavy matrix: the SAME loop with a DataParallel trainer over
    the 8-device CPU sim and grad_accum microbatching — the fit-path
    composition the tentpole claims (strategies/accum ride under the rl
    loss unchanged) — improving reward and hot-swapping every
    iteration."""
    strategy = dtpu.DataParallel()
    with strategy.scope():
        model = dtpu.Model(dtpu.models.transformer_lm(
            32, num_layers=1, d_model=16, num_heads=2, max_len=64))
        model.compile(optimizer="adam",
                      loss="sparse_categorical_crossentropy")
        model.build((32,))
    engine = Engine(model, max_slots=2, block_size=8, max_len=64,
                    temperature=1.0, seed=3)
    pt = rl.PostTrainer(model, engine, learning_rate=1e-2, kl_coef=0.01,
                        grad_accum=2, seed=0)
    rows = pt.train(_prompts(4, seed=0), iterations=3, num_samples=4,
                    max_new_tokens=16, train_epochs=2)
    rewards = [r["reward_mean"] for r in rows]
    assert rewards[-1] > rewards[0], rewards
    assert [r["weights_version"] for r in rows] == [1, 2, 3]
    # The swap re-placed the trained masters under the live strategy.
    for a, b in zip(jax.tree_util.tree_leaves(engine._params),
                    jax.tree_util.tree_leaves(model.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
