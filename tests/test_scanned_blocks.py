"""ScannedBlocks: weight-stacked lax.scan execution of identical blocks.

Parity contract: identical numerics to applying the template block
sequentially with each block's params/state slice (which is what the
unrolled Sequential would compute with the same per-block parameters).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import distributed_tpu as dtpu
from distributed_tpu import nn


def _block_fn():
    return nn.Sequential(
        [nn.Dense(8), nn.BatchNorm(), nn.Activation("relu")]
    )


def _unrolled_apply(block, stacked_p, stacked_s, x, *, train):
    h = x
    new_states = []
    n = jax.tree_util.tree_leaves(stacked_p)[0].shape[0]
    for i in range(n):
        p_i = jax.tree_util.tree_map(lambda l: l[i], stacked_p)
        s_i = jax.tree_util.tree_map(lambda l: l[i], stacked_s)
        h, ns = block.apply(p_i, s_i, h, train=train)
        new_states.append(ns)
    return h, new_states


def test_scanned_matches_unrolled_forward_and_state():
    sb = nn.ScannedBlocks(_block_fn, 3)
    params, state, out_shape = sb.init(jax.random.PRNGKey(0), (8,))
    assert out_shape == (8,)
    stacked = params["blocks"]
    assert jax.tree_util.tree_leaves(stacked)[0].shape[0] == 3

    x = jnp.asarray(
        np.random.default_rng(0).standard_normal((16, 8)), jnp.float32
    )
    y, new_state = sb.apply(params, state, x, train=True)
    y_ref, states_ref = _unrolled_apply(
        sb.block, stacked, state["blocks"], x, train=True
    )
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), rtol=2e-5,
                               atol=2e-5)
    # Stacked new state slice i == unrolled block i's new state.
    for i, ns_ref in enumerate(states_ref):
        got_i = jax.tree_util.tree_map(lambda l: l[i], new_state["blocks"])
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-5),
            got_i, ns_ref,
        )

    # Eval mode returns no new state (mirrors Sequential's omit-when-empty).
    _, es = sb.apply(params, state, x, train=False)
    assert es == {}


def test_scanned_matches_unrolled_gradients():
    sb = nn.ScannedBlocks(_block_fn, 3)
    params, state, _ = sb.init(jax.random.PRNGKey(1), (8,))
    x = jnp.asarray(
        np.random.default_rng(1).standard_normal((4, 8)), jnp.float32
    )

    def loss_scanned(p):
        y, _ = sb.apply(p, state, x, train=True)
        return jnp.sum(y**2)

    def loss_unrolled(p):
        y, _ = _unrolled_apply(sb.block, p["blocks"], state["blocks"], x,
                               train=True)
        return jnp.sum(y**2)

    g1 = jax.grad(loss_scanned)(params)
    g2 = jax.grad(loss_unrolled)(params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4),
        g1, g2,
    )


def test_scanned_blocks_validations():
    with pytest.raises(ValueError):
        nn.ScannedBlocks(_block_fn, 0)
    # Non-shape-preserving block
    with pytest.raises(ValueError):
        nn.ScannedBlocks(lambda: nn.Dense(4), 2).init(
            jax.random.PRNGKey(0), (8,)
        )
    # Decode through the stack delegates to the template block's decode:
    # a position-mixing layer without a cached override still fails loudly.
    sb = nn.ScannedBlocks(
        lambda: nn.Sequential([nn.Dense(8), nn.Lambda(lambda x: x * 2.0)]),
        2)
    params, state, _ = sb.init(jax.random.PRNGKey(0), (8,))
    with pytest.raises(NotImplementedError):
        sb.decode(params, state, sb.init_cache(params, 1, 4, jnp.float32),
                  jnp.zeros((1, 8)), pos=0)


# @slow (tier-1 budget, PR 10): 12s training e2e; forward/grad parity
# and the LM scan-trains e2e stay in-tier.
@pytest.mark.slow
def test_resnet_scan_stages_trains_and_shrinks_tree():
    kw = dict(stage_blocks=(3, 3, 3, 3), width=16, small_inputs=True)
    unrolled = dtpu.models.resnet(50, 10, **kw)
    scanned = dtpu.models.resnet(50, 10, scan_stages=True, **kw)
    pu, _, _ = unrolled.init(jax.random.PRNGKey(0), (16, 16, 3))
    ps, _, _ = scanned.init(jax.random.PRNGKey(0), (16, 16, 3))
    n_u = len(jax.tree_util.tree_leaves(pu))
    n_s = len(jax.tree_util.tree_leaves(ps))
    assert n_s < n_u  # stacked tails collapse the leaf count
    # Same total parameter count
    size = lambda t: sum(
        int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(t))
    assert size(pu) == size(ps)

    model = dtpu.Model(dtpu.models.resnet(50, 10, scan_stages=True, **kw))
    model.compile(optimizer=dtpu.optim.SGD(0.1, momentum=0.9),
                  loss="sparse_categorical_crossentropy")
    model.build((16, 16, 3))
    x = np.random.default_rng(0).standard_normal((8, 16, 16, 3)).astype(
        np.float32)
    y = np.arange(8, dtype=np.int32) % 10
    hist = model.fit(x, y, batch_size=8, epochs=2, steps_per_epoch=1,
                     verbose=0)
    assert np.isfinite(hist.history["loss"]).all()


def test_scanned_blocks_with_dropout_rng():
    sb = nn.ScannedBlocks(
        lambda: nn.Sequential([nn.Dense(8), nn.Dropout(0.5)]), 2)
    params, state, _ = sb.init(jax.random.PRNGKey(0), (8,))
    assert sb.needs_rng
    x = jnp.ones((4, 8))
    y1, _ = sb.apply(params, state, x, train=True,
                     rng=jax.random.PRNGKey(1))
    y2, _ = sb.apply(params, state, x, train=True,
                     rng=jax.random.PRNGKey(2))
    ye, _ = sb.apply(params, state, x, train=False)
    assert not np.allclose(np.asarray(y1), np.asarray(y2))
    assert np.isfinite(np.asarray(ye)).all()


def test_transformer_lm_scan_trains():
    m = dtpu.Model(dtpu.models.transformer_lm(
        64, num_layers=3, d_model=32, num_heads=4, max_len=16, scan=True))
    m.compile(optimizer=dtpu.optim.Adam(1e-3),
              loss="sparse_categorical_crossentropy")
    m.build((16,))
    x = np.zeros((4, 16), np.int32)
    h = m.fit(x, x, batch_size=4, epochs=1, steps_per_epoch=2, verbose=0)
    assert np.isfinite(h.history["loss"]).all()
    with pytest.raises(ValueError):
        dtpu.models.transformer_lm(64, scan=True, pipeline=True)
    with pytest.raises(ValueError):
        dtpu.models.transformer_lm(64, scan=True, moe_experts=2)
    with pytest.raises(ValueError):
        dtpu.models.resnet(50, 10, small_inputs=True, stem="space_to_depth")


def test_scanned_blocks_tensor_parallel_hints():
    """Inner Megatron roles survive the stack: 'col' -> last dim, 'row' ->
    dim 1 (behind the stack index) under DataTensorParallel."""
    import jax
    from jax.sharding import PartitionSpec

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    strategy = dtpu.DataTensorParallel(model_parallel=2)
    with strategy.scope():
        m = dtpu.Model(dtpu.models.transformer_lm(
            64, num_layers=2, d_model=32, num_heads=4, max_len=16, scan=True))
        m.compile(optimizer=dtpu.optim.Adam(1e-3),
                  loss="sparse_categorical_crossentropy")
        m.build((16,))
    blocks = m.params["scanned_blocks"]["blocks"]
    # FFN in-projection is 'col' (last dim over model axis)
    ffn_in = blocks["residual_1"]["main"]["dense"]["kernel"]
    assert ffn_in.sharding.spec == PartitionSpec(None, None, "model"), (
        ffn_in.sharding)
    # FFN out-projection is 'row' -> 'row1' (dim 1 over model axis)
    ffn_out = blocks["residual_1"]["main"]["dense_1"]["kernel"]
    assert ffn_out.sharding.spec == PartitionSpec(None, "model", None), (
        ffn_out.sharding)
    # And the stacked model still trains a step.
    x = np.zeros((4, 16), np.int32)
    h = m.fit(x, x, batch_size=4, epochs=1, steps_per_epoch=1, verbose=0)
    assert np.isfinite(h.history["loss"]).all()


def _restack_unrolled_into_scanned(pu, num_layers):
    """Map the unrolled LM param tree (flat residual_{2i}/residual_{2i+1})
    into the scanned layout ({"scanned_blocks": {"blocks": ...}})."""
    def name(i):
        return "residual" if i == 0 else f"residual_{i}"

    stacked = {}
    for slot in ("residual", "residual_1"):
        off = 0 if slot == "residual" else 1
        per = [pu[name(2 * i + off)] for i in range(num_layers)]
        stacked[slot] = jax.tree_util.tree_map(
            lambda *ls: jnp.stack(ls), *per)
    ps = {k: v for k, v in pu.items() if not k.startswith("residual")}
    ps["scanned_blocks"] = {"blocks": stacked}
    return ps


def test_scanned_generation_matches_unrolled():
    """Greedy generation through stacked KV caches equals the unrolled
    model's, given identical per-block parameters."""
    L = 3
    kw = dict(num_layers=L, d_model=32, num_heads=4, max_len=32)
    mu = dtpu.Model(dtpu.models.transformer_lm(64, **kw))
    mu.compile(optimizer=dtpu.optim.Adam(1e-3),
               loss="sparse_categorical_crossentropy")
    mu.build((16,), seed=7)

    ms = dtpu.Model(dtpu.models.transformer_lm(64, scan=True, **kw))
    ms.compile(optimizer=dtpu.optim.Adam(1e-3),
               loss="sparse_categorical_crossentropy")
    ms.build((16,), seed=0)
    ms.params = _restack_unrolled_into_scanned(mu.params, L)

    prompt = np.array([[5, 9, 2, 11], [1, 1, 3, 60]], np.int32)
    out_u = mu.generate(prompt, 8, temperature=0.0)
    out_s = ms.generate(prompt, 8, temperature=0.0)
    np.testing.assert_array_equal(out_u, out_s)
    # And the forward logits agree too (same restacked params).
    logits_u, _ = mu.module.apply(mu.params, {}, jnp.asarray(prompt))
    logits_s, _ = ms.module.apply(ms.params, {}, jnp.asarray(prompt))
    np.testing.assert_allclose(np.asarray(logits_u), np.asarray(logits_s),
                               rtol=2e-5, atol=2e-5)
