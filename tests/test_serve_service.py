"""dtpu-serve: framing, transport, quotas, and the real-process service.

Layering mirrors the subsystem: the jax-free pieces (protocol framing,
payload transport, token buckets) are pinned from plain sockets and
tmpdirs with no model anywhere; the service tests then spawn REAL worker
processes (``python -m distributed_tpu.serve_service.worker``) and hold
the same decisive contract the fleet pinned in-process — every request
served through the service, whatever kills or transport failures happen
around it, produces exactly the tokens a sequential ``generate()``
produces.

Kept lean for the 1-core tier-1 box: worker spin-up is ~3 s (cold jax
import + build + first compile per process), so ONE single-worker
end-to-end test rides in tier-1 and the multi-process matrix (prefill
handoff over shm, kill-a-replica, cross-process pool mismatch) is @slow.
"""

import io
import os
import socket

import numpy as np
import pytest

import distributed_tpu as dtpu
from distributed_tpu.fleet import pack_kv
from distributed_tpu.serve_service import (
    MAGIC, ProtocolError, ServeService, ServeSpec, TenantQuotas,
    TokenBucket, TransportError, ShmTransport, decode_payload,
    encode_payload, handoff_to_payload, payload_to_handoff, recv_exact,
    recv_frame, send_frame,
)
from distributed_tpu.serving import Request
from distributed_tpu.serving.kv_cache import PagedKVCache
from distributed_tpu.utils.events import read_events

# --------------------------------------------------------------- protocol --


def _pair():
    a, b = socket.socketpair()
    a.settimeout(5.0)
    b.settimeout(5.0)
    return a, b


def test_frame_roundtrip_header_and_blobs():
    a, b = _pair()
    blobs = (b"\x00" * 17, b"payload-two", b"")
    send_frame(a, {"type": "submit", "request_id": 3}, blobs)
    header, got = recv_frame(b)
    assert header == {"type": "submit", "request_id": 3}
    assert [bytes(x) for x in got] == list(blobs)
    # _blobs is framing-internal: popped before the header is returned.
    assert "_blobs" not in header
    a.close(), b.close()


def test_clean_eof_between_frames_is_none():
    a, b = _pair()
    send_frame(a, {"type": "hello"})
    a.close()
    assert recv_frame(b)[0] == {"type": "hello"}
    assert recv_frame(b) is None
    b.close()


@pytest.mark.parametrize("cut", [2, 4, 6, 10])
def test_torn_frame_raises_at_every_boundary(cut):
    """A peer dying mid-send must surface as ProtocolError — inside the
    magic (2), after it (4), inside the header length (6), and inside
    the header body (10). Never a short-but-plausible frame."""
    buf = io.BytesIO()

    class _Sink:
        def sendall(self, data):
            buf.write(data)

    send_frame(_Sink(), {"type": "submit", "request_id": 1}, (b"kv",))
    wire = buf.getvalue()
    a, b = _pair()
    a.sendall(wire[:cut])
    a.close()
    with pytest.raises(ProtocolError):
        recv_frame(b)
    b.close()


def test_torn_blob_raises():
    buf = io.BytesIO()

    class _Sink:
        def sendall(self, data):
            buf.write(data)

    send_frame(_Sink(), {"type": "prefilled"}, (b"x" * 64,))
    wire = buf.getvalue()
    a, b = _pair()
    a.sendall(wire[:-10])  # last blob short by 10 bytes
    a.close()
    with pytest.raises(ProtocolError):
        recv_frame(b)
    b.close()


def test_bad_magic_and_corrupt_length_raise():
    a, b = _pair()
    a.sendall(b"HTTP" + b"\x00" * 16)
    with pytest.raises(ProtocolError, match="magic"):
        recv_frame(b)
    a2, b2 = _pair()
    a2.sendall(MAGIC + b"\xff\xff\xff\xff")  # 4 GiB header: corrupt
    with pytest.raises(ProtocolError, match="header length"):
        recv_frame(b2)
    for s in (a, b, a2, b2):
        s.close()


def test_recv_exact_short_read():
    a, b = _pair()
    a.sendall(b"abc")
    a.close()
    with pytest.raises(ProtocolError, match="3 of 5"):
        recv_exact(b, 5)
    b.close()


# -------------------------------------------------------------- transport --


def _payload(seed=0, nblocks=3):
    rng = np.random.default_rng(seed)
    return {
        "blocks": {
            f"layer{i}/k@0@(4,2)": rng.standard_normal((4, 2)).astype(
                np.float32)
            for i in range(nblocks)
        },
        "cached_len": 9,
        "block_size": 4,
        "dtype": "float32",
        "prefix_hashes": [1, 2, 3],
        "skip_blocks": 0,
    }


def test_encode_decode_payload_roundtrip():
    p = _payload()
    meta, blobs = encode_payload(p)
    assert meta["cached_len"] == 9 and len(blobs) == len(p["blocks"])
    out = decode_payload(meta, blobs)
    assert out["block_size"] == 4 and out["prefix_hashes"] == [1, 2, 3]
    for key, arr in p["blocks"].items():
        np.testing.assert_array_equal(out["blocks"][key], arr)


def test_decode_payload_count_mismatch_and_corrupt_blob():
    meta, blobs = encode_payload(_payload())
    with pytest.raises(TransportError):
        decode_payload(meta, blobs[:-1])
    with pytest.raises(TransportError):
        decode_payload(meta, [b"not-an-npy"] + list(blobs[1:]))


def test_shm_transport_roundtrip_and_delete(tmp_path):
    tr = ShmTransport(tmp_path / "kv", owner=True)
    p = _payload(seed=1)
    ref = tr.put(p)
    out = tr.get(ref)
    for key, arr in p["blocks"].items():
        np.testing.assert_array_equal(np.asarray(out["blocks"][key]), arr)
    tr.delete(ref)
    with pytest.raises(TransportError):
        tr.get(ref)
    tr.close()
    assert not (tmp_path / "kv").exists()


def test_shm_put_is_atomic_commit(tmp_path):
    """The manifest is the commit marker (os.replace of the whole dir):
    a payload directory without one — a writer killed mid-put — must
    read as TransportError, never as a truncated payload."""
    tr = ShmTransport(tmp_path / "kv")
    ref = tr.put(_payload())
    os.unlink(os.path.join(ref["path"], "manifest.json"))
    with pytest.raises(TransportError):
        tr.get(ref)


# ----------------------------------------------------------------- quotas --


def test_token_bucket_all_or_nothing_and_refill():
    b = TokenBucket(rate=10.0, burst=20.0)
    assert b.try_take(20.0, now=0.0)       # full bucket drains
    assert not b.try_take(1.0, now=0.0)    # empty: all-or-nothing
    assert b.retry_after(1.0) == pytest.approx(0.1)
    assert b.try_take(1.0, now=0.2)        # 2 tokens refilled
    # A cost beyond burst reports the finite full-refill horizon.
    assert np.isfinite(b.retry_after(10_000.0))


def test_tenant_quotas_unlisted_unmetered():
    q = TenantQuotas({"flood": (1.0, 4.0)})
    ok, retry = q.admit("anyone", 1000.0, now=0.0)
    assert ok and retry is None
    assert q.admit("flood", 4.0, now=0.0) == (True, None)
    ok, retry = q.admit("flood", 4.0, now=0.0)
    assert not ok and retry > 0
    t = q.telemetry()
    assert t["rejected"] == 1 and t["rejected_by_tenant"] == {"flood": 1}


# ------------------------------------------------- payload <-> KVHandoff --


@pytest.fixture(scope="module")
def lm():
    model = dtpu.Model(dtpu.models.transformer_lm(
        32, num_layers=1, d_model=16, num_heads=2, max_len=64))
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy")
    model.build((64,))
    return model


def test_payload_handoff_roundtrip(lm):
    """handoff -> dict payload -> (encode/decode) -> KVHandoff preserves
    every block byte and all trim metadata."""
    import jax
    kv = PagedKVCache(lm.module, lm.params, max_slots=2, block_size=4,
                      max_blocks_per_seq=8, num_blocks=9, dtype=np.float32)
    assert kv.reserve(0, 10)
    rng = np.random.default_rng(0)
    leaves, treedef = jax.tree_util.tree_flatten(kv.caches)
    kv.caches = jax.tree_util.tree_unflatten(treedef, [
        jax.numpy.asarray(rng.normal(size=l.shape).astype(np.float32))
        for l in leaves
    ])
    prompt = np.arange(10, dtype=np.int32) % 32
    h = pack_kv(kv, 0, 10, tokens=prompt)
    p = handoff_to_payload(h)
    meta, blobs = encode_payload(p)
    back = payload_to_handoff(decode_payload(meta, blobs))
    assert back.cached_len == h.cached_len
    assert back.block_size == h.block_size
    assert back.prefix_hashes == h.prefix_hashes
    assert set(back.blocks) == set(h.blocks)
    for key in h.blocks:
        np.testing.assert_array_equal(np.asarray(back.blocks[key]),
                                      np.asarray(h.blocks[key]))


# ---------------------------------------------------------------- service --

_MODEL = dict(vocab_size=32, num_layers=1, d_model=16, num_heads=2,
              max_len=64)


def _spec(**kw):
    kw.setdefault("model", dict(_MODEL))
    kw.setdefault("build_len", 64)
    kw.setdefault("max_slots", 2)
    kw.setdefault("block_size", 4)
    kw.setdefault("max_len", 64)
    return ServeSpec(**kw)


@pytest.fixture(scope="module")
def reference(lm):
    """Sequential greedy generate() in THIS process: Model.build is
    seed-deterministic, so worker processes hold byte-identical params
    and the service outputs must match these exactly."""
    def gen(prompts, news):
        return [np.asarray(lm.generate(p[None], m, temperature=0.0)[0])
                for p, m in zip(prompts, news)]
    return gen


def _requests(n, seed=3, vocab=32, m=6):
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(0, vocab, (int(t),)).astype(np.int32)
               for t in rng.integers(2, 7, n)]
    return prompts, [m] * n


def test_service_end_to_end_streams_token_exact(lm, reference, tmp_path):
    """One real decode worker process: every output token-exact vs the
    in-process generate(), the streaming iterator delivers exactly the
    final output's generated suffix, and the wall-clock telemetry is
    sane. The multi-replica / kill / handoff matrix is @slow below."""
    os.environ["DTPU_EVENT_LOG"] = str(tmp_path / "events.jsonl")
    try:
        prompts, news = _requests(3)
        svc = ServeService(_spec(), decode_replicas=1, transport="none",
                           log_dir=tmp_path)
        with svc:
            streams = []
            for p, m in zip(prompts, news):
                adm, stream = svc.submit(Request(p, m, seed=0))
                assert adm.accepted
                streams.append(stream)
            got = [list(iter(s)) for s in streams]   # pumps the service
            outs = [s.result() for s in streams]
        ref = reference(prompts, news)
        for r, o in zip(ref, outs):
            np.testing.assert_array_equal(r, o)
        for p, toks, o in zip(prompts, got, outs):
            assert toks == [int(t) for t in o[len(p):]]
        evts = {e["event"] for e in
                read_events(os.environ["DTPU_EVENT_LOG"])}
        assert {"service_start", "replica_spawn", "stream_open"} <= evts
    finally:
        del os.environ["DTPU_EVENT_LOG"]


@pytest.mark.slow
def test_service_prefill_handoff_over_shm(lm, reference, tmp_path):
    """Disaggregated pools as real processes: prompts prefill on the
    prefill worker, KV rides /dev/shm as .npy blocks, decode installs
    without re-prefilling — and outputs stay token-exact."""
    prompts, news = _requests(3, seed=5)
    svc = ServeService(_spec(), decode_replicas=1, prefill_replicas=1,
                       transport="shm", log_dir=tmp_path)
    with svc:
        res = svc.run([Request(p, m, seed=0)
                       for p, m in zip(prompts, news)], deadline_s=180)
        stats = svc.collect_stats()
    ref = reference(prompts, news)
    for r, o in zip(ref, res):
        np.testing.assert_array_equal(r, o)
    decode = [s for s in stats.values() if s.get("role") == "decode"]
    assert sum(s["handoffs_installed"] for s in decode) == 3
    assert sum(s["handoffs_fallback"] for s in decode) == 0
    prefill = [s for s in stats.values() if s.get("role") == "prefill"]
    assert sum(s["prefills"] for s in prefill) == 3
    assert res.telemetry["lost_requests"] == 0


@pytest.mark.slow
def test_service_kill_replica_recovers_token_exact(lm, reference,
                                                   tmp_path):
    """Kill a decode worker PROCESS mid-decode: zero lost requests,
    outputs token-exact (survivor re-prefills prompt+streamed context,
    greedy continuation is deterministic), and the dead worker leaves a
    readable flight-recorder postmortem referenced from the event log."""
    os.environ["DTPU_EVENT_LOG"] = str(tmp_path / "events.jsonl")
    try:
        prompts, news = _requests(6, seed=7, m=8)
        svc = ServeService(_spec(), decode_replicas=2, transport="none",
                           respawn=False, log_dir=tmp_path)
        with svc:
            streams = []
            for p, m in zip(prompts, news):
                adm, stream = svc.submit(Request(p, m, seed=0))
                assert adm.accepted
                streams.append(stream)
            while svc.streamed_tokens < 6:
                svc._pump(0.02)
            svc.kill_replica("decode-1")
            for s in streams:
                for _ in iter(s):
                    pass
            outs = [s.result() for s in streams]
            kills = svc.kills
        ref = reference(prompts, news)
        for r, o in zip(ref, outs):
            np.testing.assert_array_equal(r, o)
        assert kills == 1
        evts = read_events(os.environ["DTPU_EVENT_LOG"])
        dead = [e for e in evts if e["event"] == "fleet_replica_killed"]
        assert dead and dead[0]["replica"] == "decode-1"
        dumps = [e for e in evts if e["event"] == "flight_dump"]
        assert dumps and os.path.exists(dumps[0]["path"])
        from distributed_tpu.obs.cli import summarize
        post = summarize(evts)
        flight = post["flight_dumps"]
        assert flight and flight[0]["readable"]
        assert flight[0]["reason"] == "replica_kill"
    finally:
        del os.environ["DTPU_EVENT_LOG"]


@pytest.mark.slow
def test_service_pool_mismatch_falls_back_to_reprefill(lm, reference,
                                                       tmp_path):
    """Heterogeneous pools across PROCESSES (prefill block_size 4,
    decode block_size 8): the incompatibility is detected pre-scatter on
    the decode side (the PR 11 HandoffIncompatible contract, now across
    a real transport), every request re-prefills, a transport_fallback
    event names the reason — and outputs are still token-exact."""
    os.environ["DTPU_EVENT_LOG"] = str(tmp_path / "events.jsonl")
    try:
        prompts, news = _requests(2, seed=9)
        svc = ServeService(_spec(), decode_replicas=1, prefill_replicas=1,
                           transport="shm", log_dir=tmp_path,
                           engine_overrides={"decode": {"block_size": 8}})
        with svc:
            res = svc.run([Request(p, m, seed=0)
                           for p, m in zip(prompts, news)], deadline_s=180)
            stats = svc.collect_stats()
        ref = reference(prompts, news)
        for r, o in zip(ref, res):
            np.testing.assert_array_equal(r, o)
        decode = [s for s in stats.values()
                  if s.get("role") == "decode"][0]
        assert decode["handoffs_installed"] == 0
        assert decode["handoffs_fallback"] == 2
        falls = [e for e in read_events(os.environ["DTPU_EVENT_LOG"])
                 if e["event"] == "transport_fallback"]
        assert len(falls) == 2
        assert all("block_size" in f["reason"] for f in falls)
    finally:
        del os.environ["DTPU_EVENT_LOG"]


@pytest.mark.slow
def test_service_quotas_and_autoscaler_live(lm, tmp_path):
    """Front-door quotas against real workers (flooder throttled before
    the queue, unmetered tenant unaffected) and the QueueAutoscaler
    driving a real second process up and back down."""
    from distributed_tpu.fleet import QueueAutoscaler
    prompts, news = _requests(10, seed=11, m=8)
    svc = ServeService(
        _spec(max_slots=1), decode_replicas=1, transport="none",
        quotas=TenantQuotas({"flood": (1.0, 12.0)}),
        autoscaler=QueueAutoscaler(min_replicas=1, max_replicas=2,
                                   queue_high=1.5, queue_low=0.25,
                                   cooldown_s=0.5),
        log_dir=tmp_path,
    )
    with svc:
        res = svc.run(
            [Request(p, m, seed=0) for p, m in zip(prompts, news)],
            tenants=["flood"] * 8 + ["paying", "paying"],
            deadline_s=180,
        )
    tel = res.telemetry
    assert tel["quotas"]["rejected"] > 0
    assert tel["lost_requests"] == 0
    assert res[8] is not None and res[9] is not None
    assert tel["decode_pool"]["spawns"] >= 2  # autoscaler spawned live
    assert any(e["to"] > e["from"]
               for e in tel["decode_pool"]["events"])
