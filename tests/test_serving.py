"""Serving runtime: continuous batching, paged KV cache, prefill/decode.

The decisive test is greedy-parity: every request served through the
engine — whatever the batch composition, block size, prefill chunking, or
preemption pressure around it — must produce exactly the tokens a
sequential per-request ``generate()`` produces. That pins the paged
attention read/write path, the per-slot position masking, the
prefill/decode handoff, and the scheduler's bookkeeping all at once.

Kept lean (tier-1 runs on a 1-core box): one tiny LM fixture shared
across the module, and each property tested at the smallest shape that
can catch its failure mode.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import assert_no_recompile

import distributed_tpu as dtpu
from distributed_tpu.serving import (
    BlockAllocator, Engine, PagedKVCache, Request,
)


@pytest.fixture(scope="module")
def lm():
    model = dtpu.Model(dtpu.models.transformer_lm(
        32, num_layers=2, d_model=16, num_heads=2, max_len=64))
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    model.build((16,))
    return model


def _requests(seed=0, n=3, vocab=32, p_range=(1, 9), m_range=(3, 9)):
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(0, vocab, (int(t),)).astype(np.int32)
               for t in rng.integers(*p_range, n)]
    news = [int(m) for m in rng.integers(*m_range, n)]
    return prompts, news


def _sequential_generate(model, prompts, news):
    return [model.generate(p[None], m, temperature=0.0)[0]
            for p, m in zip(prompts, news)]


# ------------------------------------------------------------------ parity --
def test_continuous_batching_matches_sequential_generate(lm):
    """More requests than slots, heterogeneous prompt/response lengths:
    admit-mid-decode (a finished sequence's slot is refilled while others
    keep decoding) must leave every request's greedy tokens identical to
    its solo generate()."""
    prompts, news = _requests(seed=0, n=5)
    want = _sequential_generate(lm, prompts, news)
    engine = Engine(lm, max_slots=2, block_size=4, max_len=64)
    got = engine.run([Request(p, m) for p, m in zip(prompts, news)])
    for i, (w, g) in enumerate(zip(want, got)):
        np.testing.assert_array_equal(w, g, err_msg=f"request {i}")
    t = engine.last_run_telemetry
    # 5 requests over 2 slots: later requests were admitted mid-decode.
    assert t["prefill_dispatches"] == 5
    assert t["decode_steps"] >= max(news) - 1
    assert 0.0 < t["kv_utilization"]["peak"] <= 1.0


# @slow (tier-1 budget, PR 10): 11s; still runs in TIER1_SERVE_SMOKE
# (no -m filter) and with -m slow when touching prefill.
@pytest.mark.slow
def test_prefill_chunking_matches_whole_prompt(lm):
    """The prefill/decode split at its sharpest: a chunked prefill (chunks
    attending to earlier chunks through the pool) must equal both the
    one-dispatch prefill and sequential generate()."""
    prompts = [np.arange(1, 14, dtype=np.int32) % 31]  # 13 tokens
    news = [6]
    want = _sequential_generate(lm, prompts, news)
    for chunk in (None, 4, 5):
        engine = Engine(lm, max_slots=1, block_size=4, max_len=64,
                        prefill_chunk=chunk)
        got = engine.run([Request(prompts[0], news[0])])
        np.testing.assert_array_equal(want[0], got[0],
                                      err_msg=f"prefill_chunk={chunk}")


def test_preemption_under_pool_pressure_keeps_parity(lm):
    """A pool too small for both runners forces a mid-decode preemption
    (youngest evicted, re-prefilled later); tokens must still match."""
    prompts, news = _requests(seed=3, n=2, p_range=(3, 5),
                              m_range=(24, 26))
    want = _sequential_generate(lm, prompts, news)
    # Each sequence needs up to ceil(30/4) = 8 blocks; 11 allocatable.
    engine = Engine(lm, max_slots=2, block_size=4, max_len=32,
                    num_blocks=12)
    got = engine.run([Request(p, m) for p, m in zip(prompts, news)])
    for w, g in zip(want, got):
        np.testing.assert_array_equal(w, g)
    assert engine.last_run_telemetry["preemptions"] >= 1
    assert engine.kv.live_blocks == 0  # everything returned to the pool


def test_eos_stops_a_sequence_early(lm):
    prompts, news = _requests(seed=1, n=1, m_range=(8, 9))
    full = _sequential_generate(lm, prompts, news)[0]
    t_p = prompts[0].size
    eos = int(full[t_p + 2])  # third generated token
    engine = Engine(lm, max_slots=1, block_size=4, max_len=64, eos_id=eos)
    out = engine.run([Request(prompts[0], news[0])])[0]
    # Stops at (and includes) the FIRST eos occurrence.
    stop = int(np.argmax(full[t_p:] == eos))
    np.testing.assert_array_equal(out, full[: t_p + stop + 1])


def test_engine_per_request_lifecycle_rows(lm):
    """The telemetry the fleet router/autoscaler consume: per-request
    lifecycle timestamps, tail TTFT percentiles, and live queue/pool
    signals — not just run-level means."""
    prompts, news = _requests(seed=5, n=5)
    engine = Engine(lm, max_slots=2, block_size=4, max_len=64)
    assert engine.queue_depth == 0  # idle: live signals read clean
    assert engine.free_blocks == engine.kv.allocator.num_allocatable
    engine.run([Request(p, m) for p, m in zip(prompts, news)])
    t = engine.last_run_telemetry
    rows = t["requests"]
    assert len(rows) == 5
    for row in rows:
        assert row["enqueued_s"] <= row["admitted_s"] <= \
            row["first_token_s"] <= row["finished_s"]
    ttft = t["time_to_first_token"]
    assert ttft["p50"] <= ttft["p99"] <= ttft["max"]
    assert ttft["mean"] > 0
    # 5 requests over 2 slots: a queue existed at some decode step.
    assert t["queue_depth"]["peak"] >= 1
    assert 0 <= t["free_blocks_min"] <= engine.kv.allocator.num_allocatable
    assert engine.free_blocks == engine.kv.allocator.num_allocatable


# ------------------------------------------------------- block accounting --
def test_block_allocator_accounting():
    alloc = BlockAllocator(8)  # block 0 reserved: 7 allocatable
    assert alloc.num_allocatable == 7
    a = alloc.allocate(3)
    b = alloc.allocate(4)
    assert len(a) == 3 and len(b) == 4 and not (set(a) & set(b))
    assert 0 not in a + b  # the trash block is never granted
    assert alloc.allocate(1) is None  # exhausted: all-or-nothing
    assert alloc.utilization() == 1.0
    alloc.free(a)
    assert alloc.num_free == 3
    assert alloc.utilization() == pytest.approx(4 / 7)
    with pytest.raises(ValueError, match="double free"):
        alloc.free([a[0]])
    c = alloc.allocate(3)
    assert sorted(c) == sorted(a)  # freed blocks are reused


def test_paged_cache_reserve_release_no_leaks(lm):
    kv = PagedKVCache(lm.module, lm.params, max_slots=2, block_size=4,
                      max_blocks_per_seq=5, num_blocks=8,
                      dtype=jnp.float32)
    assert kv.reserve(0, 5)  # 2 blocks
    assert kv.reserve(0, 6)  # still 2: no-op growth
    assert kv.reserve(1, 9)  # 3 blocks
    assert kv.live_blocks == 5 and kv.allocator.num_free == 2
    assert kv.utilization() == pytest.approx(5 / 7)
    # Slot 0 asking for 5 blocks total = 3 more; only 2 free: all-or-
    # nothing refusal, and the partial grant must NOT have happened.
    assert not kv.reserve(0, 20)
    assert kv.live_blocks == 5 and kv.allocator.num_free == 2
    kv.release(1)
    assert kv.live_blocks == 2 and (kv.block_tables[1] == 0).all()
    assert kv.positions[1] == 0
    assert kv.reserve(0, 20)  # now it fits
    kv.release(0)
    assert kv.live_blocks == 0 and kv.allocator.num_free == 7
    with pytest.raises(ValueError, match="per-sequence cap"):
        kv.reserve(0, 21)


def test_engine_rejects_oversized_and_impossible_requests(lm):
    engine = Engine(lm, max_slots=1, block_size=4, max_len=16)
    with pytest.raises(ValueError, match="max_len"):
        engine.run([Request(np.arange(10, dtype=np.int32) % 31, 12)])
    # Context that fits max_len but not the (tiny) pool: loud, not a hang.
    small = Engine(lm, max_slots=1, block_size=4, max_len=32, num_blocks=3)
    with pytest.raises(RuntimeError, match="pool"):
        small.run([Request(np.arange(20, dtype=np.int32) % 31, 4)])
    with pytest.raises(ValueError, match="max_len"):
        # Engine cap above the model's positional table must fail at
        # construction, not silently clamp rows mid-serve.
        Engine(lm, max_slots=1, block_size=4, max_len=128)


# ------------------------------------------------------------- precision --
def test_kv_cache_dtype_follows_precision_policy():
    """The paged pool dtype derives from the PR 5 policy exactly like
    generate()'s dense cache (Model.decode_dtype)."""
    def build(precision):
        m = dtpu.Model(dtpu.models.transformer_lm(
            32, num_layers=1, d_model=16, num_heads=2, max_len=32))
        m.compile(optimizer="adam",
                  loss="sparse_categorical_crossentropy",
                  precision=precision)
        m.build((16,))
        return m

    m32 = build(None)
    e32 = Engine(m32, max_slots=1, block_size=4, max_len=32)
    assert all(l.dtype == jnp.float32
               for l in jax.tree_util.tree_leaves(e32.kv.caches))

    mbf = build("mixed_bfloat16")
    ebf = Engine(mbf, max_slots=1, block_size=4, max_len=32)
    assert all(l.dtype == jnp.bfloat16
               for l in jax.tree_util.tree_leaves(ebf.kv.caches))
    # And the policy engine still serves end-to-end.
    out = ebf.run([Request(np.array([1, 2, 3], np.int32), 3)])[0]
    assert out.shape == (6,) and out.dtype == np.int32


# ------------------------------------------- logprobs, RNG, weight swaps --
@pytest.fixture(scope="module")
def sampler(lm):
    """One shared SAMPLING engine (temperature 1): every fresh Engine
    pays its own prefill/decode compile, so the logprob/RNG tests reuse
    this one — per-request seeds make their streams independent anyway
    (that independence is exactly what the tests pin)."""
    return Engine(lm, max_slots=2, block_size=4, max_len=64,
                  temperature=1.0, seed=5)


def test_logprob_capture_rides_fixed_dispatch_no_recompile(lm, sampler):
    """return_logprobs toggling is pure host bookkeeping: the logprobs
    are computed inside the fixed-shape dispatches either way, so the
    decode/prefill jit caches must not grow across the toggle — and the
    captured values must equal teacher-forced log-softmax scores of the
    served tokens (the trainer's recomputation, see rl.PostTrainer)."""
    prompts, news = _requests(seed=7, n=2, m_range=(4, 6))
    reqs = lambda: [Request(p, m, seed=i)
                    for i, (p, m) in enumerate(zip(prompts, news))]
    outs = sampler.run(reqs(), return_logprobs=True)
    rows_by_order = sampler.last_run_telemetry["requests"]  # submit order
    # Teacher-force both served rows in ONE padded predict (one compile):
    # captured logprob == log_softmax of the model's logits at the
    # sampled token (temperature 1).
    pad_to = max(o.size for o in outs)
    batch = np.zeros((len(outs), pad_to - 1), np.int32)
    for i, o in enumerate(outs):
        batch[i, : o.size - 1] = o[:-1]
    logits = lm.predict(batch, batch_size=len(outs))
    refs = jax.nn.log_softmax(jnp.asarray(logits, jnp.float32), -1)
    for i, (p, out, row) in enumerate(zip(prompts, outs, rows_by_order)):
        lps = row["logprobs"]
        assert len(lps) == out.size - p.size
        for t in range(p.size - 1, out.size - 1):
            want = float(refs[i, t, out[t + 1]])
            got = lps[t - (p.size - 1)]
            assert abs(want - got) < 1e-4, (t, want, got)
    # Toggling capture OFF reuses the exact same compiled programs.
    with assert_no_recompile(sampler._decode_jit, sampler._prefill_jit):
        sampler.run(reqs())
    assert "logprobs" not in sampler.last_run_telemetry["requests"][0]


def test_sampled_decode_deterministic_across_slots_and_runs(lm, sampler):
    """The serving analogue of the greedy token-exact discipline: with
    per-request seeds, sampled rollouts are bit-identical across engine
    shapes (a different max_slots changes scheduling entirely), across
    repeat runs, and sensitive to the request seed (distinct streams)."""
    prompts, news = _requests(seed=9, n=4, m_range=(5, 8))

    def serve(engine, base_seed=100):
        return engine.run([Request(p, m, seed=base_seed + i)
                           for i, (p, m) in enumerate(zip(prompts, news))])

    narrow = Engine(lm, max_slots=1, block_size=4, max_len=64,
                    temperature=1.0, seed=5)
    a, b, c = serve(narrow), serve(sampler), serve(sampler)
    for i, (x, y, z) in enumerate(zip(a, b, c)):
        np.testing.assert_array_equal(x, y, err_msg=f"slots 1 vs 2, req {i}")
        np.testing.assert_array_equal(y, z, err_msg=f"rerun, req {i}")
    # Different request seeds are different sampling streams.
    d = serve(sampler, base_seed=900)
    assert any(not np.array_equal(x, y) for x, y in zip(a, d))


def test_update_weights_staleness_contract(lm):
    """A sequence straddling a hot-swap keeps its KV and finishes, with
    the weights_version boundary recorded per token row. Swapping in
    value-identical params mid-run must leave greedy tokens exactly equal
    to the unswapped run (KV retained, no hidden reset); the jit cache
    must not grow (same shapes/dtypes => no retrace)."""
    prompts, news = _requests(seed=4, n=1, p_range=(4, 5), m_range=(8, 9))
    engine = Engine(lm, max_slots=1, block_size=4, max_len=64)
    base = engine.run([Request(prompts[0], news[0])])[0]
    same = jax.tree_util.tree_map(lambda a: a, lm.params)

    def swap(eng, step):
        if step == 3:
            eng.update_weights(same)

    with assert_no_recompile(engine._decode_jit):
        out = engine.run([Request(prompts[0], news[0])],
                         on_decode_step=swap)[0]
    np.testing.assert_array_equal(base, out)
    row = engine.last_run_telemetry["requests"][0]
    # Prefill token + 3 decode tokens under v0, the rest under v1.
    assert row["weights_versions"] == [
        {"version": 0, "tokens": 4},
        {"version": 1, "tokens": news[0] - 4},
    ]
    assert engine.last_run_telemetry["weight_swaps"] == 1
    assert engine.weights_version == 1
    assert engine.kv.live_blocks == 0  # the straddler finished cleanly
    # Genuinely new weights mid-run: sequence still completes, and the
    # engine keeps serving them (version sticks) on the next run.
    bumped = jax.tree_util.tree_map(
        lambda a: a + 0.05 * jnp.ones_like(a), lm.params
    )

    def swap2(eng, step):
        if step == 2:
            eng.update_weights(bumped)

    out2 = engine.run([Request(prompts[0], news[0])], on_decode_step=swap2)[0]
    assert out2.shape == base.shape
    assert engine.weights_version == 2
    after = engine.run([Request(prompts[0], news[0])])[0]
    spans = engine.last_run_telemetry["requests"][0]["weights_versions"]
    assert spans == [{"version": 2, "tokens": news[0]}]
    assert not np.array_equal(after, base)  # bumped weights really serve


def test_update_weights_validates_loudly(lm):
    engine = Engine(lm, max_slots=1, block_size=4, max_len=64)
    with pytest.raises(ValueError, match="structure"):
        engine.update_weights({"bogus": np.zeros((2, 2), np.float32)})
    wrong_shape = jax.tree_util.tree_map(
        lambda a: np.zeros(a.shape + (1,), np.float32), lm.params
    )
    with pytest.raises(ValueError, match="shape mismatch"):
        engine.update_weights(wrong_shape)
    wrong_dtype = jax.tree_util.tree_map(
        lambda a: np.zeros(a.shape, np.float16), lm.params
    )
    with pytest.raises(ValueError, match="dtype mismatch"):
        engine.update_weights(wrong_dtype)
    assert engine.weights_version == 0  # failed swaps change nothing


# -------------------------------------------------- stacked-block serving --
@pytest.fixture(scope="module")
def scanned_lm():
    """ScannedBlocks LM: one weight-stacked block, paged pools carried
    under the reserved 'stacked' key with a leading (S, ...) stage dim."""
    model = dtpu.Model(dtpu.models.transformer_lm(
        32, num_layers=3, d_model=16, num_heads=2, max_len=64, scan=True))
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    model.build((16,))
    return model


def test_scanned_stack_paged_parity_and_batch_churn(scanned_lm):
    """The tentpole's serving leg: a ScannedBlocks LM served through the
    paged engine is token-exact against its own dense generate(), and a
    second run with a different batch composition reuses the exact same
    compiled prefill/decode programs (the stacked pool rides the fixed
    dispatch shapes)."""
    prompts, news = _requests(seed=11, n=4)
    want = _sequential_generate(scanned_lm, prompts, news)
    engine = Engine(scanned_lm, max_slots=2, block_size=4, max_len=64)
    got = engine.run([Request(p, m) for p, m in zip(prompts, news)])
    for i, (w, g) in enumerate(zip(want, got)):
        np.testing.assert_array_equal(w, g, err_msg=f"request {i}")
    assert engine.kv.live_blocks == 0
    # Batch churn: different request count/lengths, zero new compiles.
    prompts2, news2 = _requests(seed=12, n=3, p_range=(2, 7),
                                m_range=(4, 8))
    want2 = _sequential_generate(scanned_lm, prompts2, news2)
    with assert_no_recompile(engine._decode_jit, engine._prefill_jit):
        got2 = engine.run([Request(p, m)
                           for p, m in zip(prompts2, news2)])
    for w, g in zip(want2, got2):
        np.testing.assert_array_equal(w, g)


def test_scanned_stack_composes_fused_and_prefix(scanned_lm):
    """PR 18's fused decode kernel and PR 16's prefix cache both reach
    the stacked pool through the same hooks: parity must hold with the
    fused kernel selected, and again with the prefix store sharing a
    common prompt head across requests."""
    rng = np.random.default_rng(5)
    common = rng.integers(0, 31, (16,)).astype(np.int32)
    prompts = [np.concatenate([common, np.array([t], np.int32)])
               for t in (3, 9, 17, 26)]
    news = [6, 7, 5, 6]
    want = _sequential_generate(scanned_lm, prompts, news)
    fused = Engine(scanned_lm, max_slots=2, block_size=4, max_len=64,
                   decode_kernel="fused")
    got = fused.run([Request(p, m) for p, m in zip(prompts, news)])
    for w, g in zip(want, got):
        np.testing.assert_array_equal(w, g)
    both = Engine(scanned_lm, max_slots=2, block_size=4, max_len=64,
                  decode_kernel="fused", prefix_cache=True)
    got2 = both.run([Request(p, m) for p, m in zip(prompts, news)])
    for w, g in zip(want, got2):
        np.testing.assert_array_equal(w, g)
    # The waves behind the first two slots re-read the shared 16-token
    # head (4 full blocks) from the store instead of recomputing it.
    rep = both.last_run_telemetry["prefix_cache"]
    assert rep["hit_blocks"] > 0 and rep["hit_tokens"] > 0


def test_pipelined_blocks_serve_paged_off_pipe_mesh():
    """PipelinedBlocks serves through the same stacked hooks on its
    sequential single-device path — training topology (pipe mesh) and
    serving topology are independent choices."""
    model = dtpu.Model(dtpu.models.transformer_lm(
        32, num_layers=2, d_model=16, num_heads=2, max_len=64,
        pipeline=True))
    model.compile(optimizer="adam",
                  loss="sparse_categorical_crossentropy")
    model.build((16,))
    prompts, news = _requests(seed=13, n=2)
    want = _sequential_generate(model, prompts, news)
    engine = Engine(model, max_slots=2, block_size=4, max_len=64)
    got = engine.run([Request(p, m) for p, m in zip(prompts, news)])
    for w, g in zip(want, got):
        np.testing.assert_array_equal(w, g)


def test_pipelined_paged_on_live_pipe_mesh_raises(devices):
    """On a live pipe mesh the paged pool would split across ranks while
    the allocator/prefix state assumes one address space — a loud raise,
    not a silent gather."""
    from distributed_tpu import nn

    model = dtpu.Model(dtpu.models.transformer_lm(
        32, num_layers=2, d_model=16, num_heads=2, max_len=64,
        pipeline=True))
    model.compile(optimizer="adam",
                  loss="sparse_categorical_crossentropy")
    model.build((16,))
    pb = next(l for l in model.module.layers
              if isinstance(l, nn.PipelinedBlocks))

    def subtree(p):  # the layer's own params ({"blocks": ...})
        if isinstance(p, dict):
            if "blocks" in p:
                return p
            for v in p.values():
                found = subtree(v)
                if found is not None:
                    return found
        return None

    strategy = dtpu.DataPipelineParallel(pipeline_parallel=2)
    with strategy.scope():
        with pytest.raises(NotImplementedError, match="single-device"):
            pb.init_paged_cache(subtree(model.params), 8, 4, jnp.float32)
        with pytest.raises(NotImplementedError, match="single-device"):
            pb.paged_decode(subtree(model.params), {}, {},
                            jnp.zeros((1, 1, 16)),
                            block_tables=jnp.zeros((1, 8), jnp.int32),
                            positions=jnp.zeros((1,), jnp.int32))
