"""ShardedCheckpointer: per-process shard files + manifest, restore under a
different mesh shape, and the no-full-host-array guarantee (VERDICT round 2,
item 3 — the npz Checkpointer gathers O(total params) per host, which is the
wrong design for FSDP-scale models)."""

import json

import jax
import numpy as np
import pytest

import distributed_tpu as dtpu
from distributed_tpu.checkpoint import ShardCorruptionError
from distributed_tpu.checkpoint import sharded as sharded_lib
from distributed_tpu.checkpoint.sharded import _block_key, _parse_key


def _data(n=64):
    x, y = dtpu.data.synthetic_images(n, (28, 28), 10, seed=3)
    return x[..., None].astype(np.float32) / 255.0, y


def _fsdp_model(devices=None):
    strategy = dtpu.FullyShardedDataParallel()
    with strategy.scope():
        m = dtpu.Model(dtpu.models.mnist_cnn())
        m.compile(optimizer=dtpu.optim.SGD(0.05, momentum=0.9),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    return m


def test_key_roundtrip():
    k = _block_key("params/dense/kernel", (128, 0), (128, 64))
    assert _parse_key(k) == ("params/dense/kernel", (128, 0), (128, 64))
    k = _block_key("params/bias", (), ())  # scalar leaf
    assert _parse_key(k) == ("params/bias", (), ())


class TestShardedRoundTrip:
    def test_fsdp_roundtrip_no_full_host_array(self, devices, tmp_path):
        x, y = _data()
        m = _fsdp_model()
        m.fit(x, y, batch_size=32, epochs=1, verbose=0)
        before = m.evaluate(x, y, batch_size=32, verbose=0)

        ck = dtpu.ShardedCheckpointer(tmp_path)
        ck.save(m)
        # The dense1 kernel (5408, 64) f32 shards 8 ways: the largest block
        # any host touched must be its shard size, NOT its full size — the
        # format's whole reason to exist.
        dense_full = 5408 * 64 * 4
        assert ck.last_max_block_bytes < dense_full
        assert ck.last_max_block_bytes >= dense_full // 8

        m2 = _fsdp_model()
        m2.build((28, 28, 1))
        step = ck.restore_into(m2)
        assert step == m.step
        assert ck.last_max_block_bytes < dense_full  # restore side too
        after = m2.evaluate(x, y, batch_size=32, verbose=0)
        assert before == after
        # params bit-identical, shardings preserved
        for a, b in zip(jax.tree_util.tree_leaves(m.params),
                        jax.tree_util.tree_leaves(m2.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            assert a.sharding.is_equivalent_to(b.sharding, a.ndim)
        # optimizer momentum restored too (bit-identical training continues)
        for a, b in zip(jax.tree_util.tree_leaves(m.opt_state),
                        jax.tree_util.tree_leaves(m2.opt_state)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_restore_across_changed_mesh(self, devices, tmp_path):
        """Save under FSDP(8), restore under plain DP (replicated params):
        block reassembly reshards on read, so the mesh/axis layout at
        restore time need not match the one at save time."""
        x, y = _data()
        m = _fsdp_model()
        m.fit(x, y, batch_size=32, epochs=1, verbose=0)
        before = m.evaluate(x, y, batch_size=32, verbose=0)
        ck = dtpu.ShardedCheckpointer(tmp_path)
        ck.save(m)

        with dtpu.DataParallel().scope():
            m2 = dtpu.Model(dtpu.models.mnist_cnn())
            m2.compile(optimizer=dtpu.optim.SGD(0.05, momentum=0.9),
                       loss="sparse_categorical_crossentropy",
                       metrics=["accuracy"])
        m2.build((28, 28, 1))
        ck.restore_into(m2)
        after = m2.evaluate(x, y, batch_size=32, verbose=0)
        assert before == after
        for a, b in zip(jax.tree_util.tree_leaves(m.params),
                        jax.tree_util.tree_leaves(m2.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_training_resumes_bit_identically(self, devices, tmp_path):
        """fit -> save -> more fit must equal fit -> save -> restore ->
        more fit (same batches via the step cursor)."""
        x, y = _data(128)
        m = _fsdp_model()
        m.fit(x, y, batch_size=32, epochs=1, verbose=0)
        ck = dtpu.ShardedCheckpointer(tmp_path)
        ck.save(m)
        m.fit(x, y, batch_size=32, epochs=2, initial_epoch=1, verbose=0)

        m2 = _fsdp_model()
        m2.build((28, 28, 1))
        ck.restore_into(m2)
        m2.fit(x, y, batch_size=32, epochs=2, initial_epoch=1, verbose=0)
        for a, b in zip(jax.tree_util.tree_leaves(m.params),
                        jax.tree_util.tree_leaves(m2.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestShardedLifecycle:
    def test_manifest_is_commit_marker(self, devices, tmp_path):
        m = _fsdp_model()
        m.build((28, 28, 1))
        ck = dtpu.ShardedCheckpointer(tmp_path)
        ck.save(m, step=5)
        assert ck.all_steps() == [5]
        # A dir without manifest.json (aborted save) is invisible.
        (tmp_path / "ckpt-9").mkdir()
        assert ck.all_steps() == [5]
        # Corrupt: manifest promises shards that are missing.
        mandir = tmp_path / "ckpt-5"
        manifest = json.loads((mandir / "manifest.json").read_text())
        manifest["nprocs"] = 2
        (mandir / "manifest.json").write_text(json.dumps(manifest))
        m2 = _fsdp_model()
        m2.build((28, 28, 1))
        with pytest.raises(FileNotFoundError, match="proc-1"):
            ck.restore_into(m2, step=5)

    def test_gc_keeps_latest(self, devices, tmp_path):
        m = _fsdp_model()
        m.build((28, 28, 1))
        ck = dtpu.ShardedCheckpointer(tmp_path, keep=2)
        for s in (1, 2, 3, 4):
            ck.save(m, step=s)
        assert ck.all_steps() == [3, 4]

    def test_restore_empty_raises(self, devices, tmp_path):
        m = _fsdp_model()
        m.build((28, 28, 1))
        with pytest.raises(FileNotFoundError):
            dtpu.ShardedCheckpointer(tmp_path / "nope").restore_into(m)

    def test_wrong_model_raises(self, devices, tmp_path):
        m = _fsdp_model()
        m.build((28, 28, 1))
        ck = dtpu.ShardedCheckpointer(tmp_path)
        ck.save(m, step=1)
        with dtpu.FullyShardedDataParallel().scope():
            other = dtpu.Model(
                dtpu.nn.Sequential([dtpu.nn.Flatten(), dtpu.nn.Dense(10)])
            )
            other.compile(optimizer="sgd",
                          loss="sparse_categorical_crossentropy")
        other.build((28, 28, 1))
        with pytest.raises((KeyError, ValueError)):
            ck.restore_into(other, step=1)


def _repartition(step_dir, nprocs):
    """Rewrite a saved sharded checkpoint as if ``nprocs`` processes had
    written it: round-robin the saved blocks across proc-0..N-1.npz and
    patch the manifest — a faithful on-disk image of an N-process save
    (restore never cares WHICH proc file holds a block, only that the
    block index covers every leaf). The true process-count change is
    exercised end-to-end by the elastic gang tests (tests/test_elastic.py
    @slow: a 4-process-written checkpoint restored by a 2-process gang and
    2->4); this helper lets tier-1 pin the multi-file block-index path
    without spawning gangs."""
    blocks = {}
    for f in sorted(step_dir.glob("proc-*.npz")):
        with np.load(f, allow_pickle=False) as z:
            for k in z.files:
                blocks[k] = z[k]
        f.unlink()
    keys = sorted(blocks)
    for i in range(nprocs):
        np.savez(step_dir / f"proc-{i}.npz",
                 **{k: blocks[k] for k in keys[i::nprocs]})
    manifest = json.loads((step_dir / "manifest.json").read_text())
    manifest["nprocs"] = nprocs
    (step_dir / "manifest.json").write_text(json.dumps(manifest))


class TestElasticRestore:
    """N->N' restore through the block index (ISSUE 7): checkpoints laid
    out as 4- and 2-process saves restore into gangs of a different
    world/strategy, optimizer state and the runtime-set
    ``inject_hyperparams`` learning rate included."""

    def _trained(self, strategy, tmp_path, lr=3.3e-4):
        with strategy.scope():
            m = dtpu.Model(dtpu.models.mnist_cnn())
            m.compile(optimizer=dtpu.optim.SGD(0.05, momentum=0.9),
                      loss="sparse_categorical_crossentropy",
                      metrics=["accuracy"])
        x, y = _data(64)
        m.fit(x, y, batch_size=32, epochs=1, verbose=0, seed=0)
        m.set_learning_rate(lr)  # must survive the resized restore
        ck = dtpu.ShardedCheckpointer(tmp_path)
        ck.save(m)
        return m, ck, (x, y)

    def _assert_restored(self, m, m2, xy):
        x, y = xy
        assert m2.step == m.step
        assert abs(m2.get_learning_rate() - m.get_learning_rate()) < 1e-9
        for a, b in zip(jax.tree_util.tree_leaves(m.params),
                        jax.tree_util.tree_leaves(m2.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree_util.tree_leaves(m.opt_state),
                        jax.tree_util.tree_leaves(m2.opt_state)):
            np.testing.assert_array_equal(
                np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b)))
        assert (m.evaluate(x, y, batch_size=32, verbose=0)
                == m2.evaluate(x, y, batch_size=32, verbose=0))

    def test_zero1_4proc_layout_restores_into_smaller_world(
            self, devices, tmp_path):
        """A ZeRO-1 checkpoint in 4-process layout restores under the live
        (smaller-world) runtime: momentum comes back data-sharded from
        blocks scattered over all four proc files, and training continues."""
        m, ck, xy = self._trained(dtpu.ZeroDataParallel(), tmp_path)
        _repartition(tmp_path / f"ckpt-{m.step}", 4)

        with dtpu.ZeroDataParallel().scope():
            m2 = dtpu.Model(dtpu.models.mnist_cnn())
            m2.compile(optimizer=dtpu.optim.SGD(0.05, momentum=0.9),
                       loss="sparse_categorical_crossentropy",
                       metrics=["accuracy"])
        m2.build((28, 28, 1))
        ck.restore_into(m2)
        self._assert_restored(m, m2, xy)
        x, y = xy
        m2.fit(x, y, batch_size=32, epochs=1, steps_per_epoch=1, verbose=0,
               seed=0, initial_epoch=0)
        assert m2.step == m.step + 1

    def test_fsdp_2proc_layout_restores_into_larger_world_and_strategy(
            self, devices, tmp_path):
        """The grow direction, composed with a strategy change: an FSDP
        checkpoint in 2-process layout restores into a ZeRO-1 model — the
        block index reassembles each leaf from both proc files under the
        NEW strategy's placement."""
        m, ck, xy = self._trained(dtpu.FullyShardedDataParallel(), tmp_path)
        _repartition(tmp_path / f"ckpt-{m.step}", 2)

        with dtpu.ZeroDataParallel().scope():
            m2 = dtpu.Model(dtpu.models.mnist_cnn())
            m2.compile(optimizer=dtpu.optim.SGD(0.05, momentum=0.9),
                       loss="sparse_categorical_crossentropy",
                       metrics=["accuracy"])
        m2.build((28, 28, 1))
        ck.restore_into(m2)
        self._assert_restored(m, m2, xy)
        # restored under the LIVE strategy: params replicated (ZeRO-1),
        # not FSDP-sharded like the save
        from jax.sharding import PartitionSpec

        assert (m2.params["dense"]["kernel"].sharding.spec
                == PartitionSpec())


def _tamper_block(proc_file):
    """Flip one element of one block but keep the ORIGINAL per-block CRC
    map (and a structurally valid, zip-CRC-consistent npz): content
    corruption only the framework's own block CRC can catch."""
    with np.load(proc_file, allow_pickle=False) as z:
        data = {k: z[k] for k in z.files}
    key = next(k for k in sorted(data)
               if k != sharded_lib.CRC_KEY and data[k].size)
    tampered = data[key].copy()
    tampered.flat[0] = tampered.flat[0] + 1
    data[key] = tampered
    np.savez(open(proc_file, "wb"), **data)
    return key


class TestBlockCRCAndFallback:
    """ISSUE 13 satellite: corrupt blocks are caught on read (CRC, the
    data/records.py idiom), named precisely, and auto-restore falls back
    to the previous retained step instead of deserializing garbage."""

    def _saved(self, tmp_path, steps=(2, 4)):
        m = _fsdp_model()
        m.build((28, 28, 1))
        ck = dtpu.ShardedCheckpointer(tmp_path)
        for s in steps:
            ck.save(m, step=s)
        return m, ck

    def test_crc_mismatch_is_loud_and_block_addressed(self, devices,
                                                      tmp_path):
        m, ck = self._saved(tmp_path)
        key = _tamper_block(tmp_path / "ckpt-4" / "proc-0.npz")
        m2 = _fsdp_model()
        m2.build((28, 28, 1))
        with pytest.raises(ShardCorruptionError, match="CRC mismatch") as ei:
            ck.restore_into(m2, step=4)  # explicit step: never substitutes
        assert key in str(ei.value)           # names the block
        assert "proc-0.npz" in str(ei.value)  # and the file

    def test_auto_restore_falls_back_to_previous_step(self, devices,
                                                      tmp_path, monkeypatch):
        from distributed_tpu.utils import events as events_lib

        monkeypatch.setenv(events_lib.ENV_VAR, str(tmp_path / "ev.jsonl"))
        m, ck = self._saved(tmp_path)
        _tamper_block(tmp_path / "ckpt-4" / "proc-0.npz")
        m2 = _fsdp_model()
        m2.build((28, 28, 1))
        assert ck.restore_into(m2) == 2
        for a, b in zip(jax.tree_util.tree_leaves(m.params),
                        jax.tree_util.tree_leaves(m2.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        ev = events_lib.read_events(tmp_path / "ev.jsonl")
        skip = next(e for e in ev if e["event"] == "corrupt_checkpoint_skipped")
        assert skip["step"] == 4 and "CRC" in skip["error"]

    def test_garbage_shard_file_falls_back_too(self, devices, tmp_path):
        """faults.corrupt_latest_checkpoint drives the torn-write flavor
        (garbage where the npz should be) through the same fallback."""
        from distributed_tpu.resilience import corrupt_latest_checkpoint

        m, ck = self._saved(tmp_path)
        hit = corrupt_latest_checkpoint(tmp_path)
        assert hit == tmp_path / "ckpt-4" / "proc-0.npz"
        m2 = _fsdp_model()
        m2.build((28, 28, 1))
        assert ck.restore_into(m2) == 2

    def test_all_steps_corrupt_raises(self, devices, tmp_path):
        m, ck = self._saved(tmp_path)
        for s in (2, 4):
            _tamper_block(tmp_path / f"ckpt-{s}" / "proc-0.npz")
        m2 = _fsdp_model()
        m2.build((28, 28, 1))
        with pytest.raises(FileNotFoundError, match="corrupt"):
            ck.restore_into(m2)


class TestAsyncShardedSave:
    """ISSUE 13 satellite: the async_save=True + sharded=True restriction
    is lifted — shard writes background on "dtpu-shard-writer", the
    cross-host commit defers to the next main-thread touchpoint."""

    def test_commit_is_deferred_to_wait(self, devices, tmp_path):
        m = _fsdp_model()
        m.build((28, 28, 1))
        ck = dtpu.ShardedCheckpointer(tmp_path, async_save=True)
        ck.save(m, step=1)
        # The step is invisible until the deferred commit runs: an
        # uncommitted async save is an aborted save, exactly like a
        # mid-write crash.
        ck.wait()
        assert ck.all_steps() == [1]
        # A following save is the other commit touchpoint.
        ck.save(m, step=2)
        ck.save(m, step=3)
        assert 2 in ck.all_steps()
        ck.wait()
        assert ck.all_steps() == [1, 2, 3]

    def test_async_roundtrip_bit_identical(self, devices, tmp_path):
        x, y = _data()
        m = _fsdp_model()
        m.fit(x, y, batch_size=32, epochs=1, verbose=0)
        ck = dtpu.ShardedCheckpointer(tmp_path, async_save=True)
        ck.save(m)
        m2 = _fsdp_model()
        m2.build((28, 28, 1))
        # restore flushes + commits the pending write itself
        assert ck.restore_into(m2) == m.step
        for a, b in zip(jax.tree_util.tree_leaves(m.opt_state),
                        jax.tree_util.tree_leaves(m2.opt_state)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_writer_error_surfaces_and_aborts_commit(self, devices,
                                                     tmp_path, monkeypatch):
        m = _fsdp_model()
        m.build((28, 28, 1))
        ck = dtpu.ShardedCheckpointer(tmp_path, async_save=True)

        def boom(path, blocks):
            raise OSError("disk full")

        monkeypatch.setattr(sharded_lib, "_write_proc_npz", boom)
        ck.save(m, step=1)
        with pytest.raises(OSError, match="disk full"):
            ck.wait()
        assert ck.all_steps() == []  # never committed

    def test_model_checkpoint_async_sharded_no_longer_raises(
            self, devices, tmp_path):
        x, y = _data(128)
        m = _fsdp_model()
        m.fit(x, y, batch_size=32, epochs=2, verbose=0, seed=0,
              callbacks=[dtpu.callbacks.ModelCheckpoint(
                  tmp_path, sharded=True, save_freq=2, async_save=True)])
        # train-end wait() committed the newest step
        ck = dtpu.ShardedCheckpointer(tmp_path)
        assert ck.latest_step() == m.step
        m2 = _fsdp_model()
        m2.fit(x, y, batch_size=32, epochs=2, verbose=0, seed=0,
               callbacks=[dtpu.callbacks.ModelCheckpoint(
                   tmp_path, sharded=True, restore=True)])
        for a, b in zip(jax.tree_util.tree_leaves(m.params),
                        jax.tree_util.tree_leaves(m2.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_model_checkpoint_callback_sharded(devices, tmp_path):
    """ModelCheckpoint(sharded=True) saves per-process files and a crash
    relaunch resumes from them."""
    x, y = _data(128)
    m = _fsdp_model()
    m.fit(x, y, batch_size=32, epochs=2, verbose=0,
          callbacks=[dtpu.callbacks.ModelCheckpoint(tmp_path, sharded=True)])
    assert (tmp_path / f"ckpt-{m.step}" / "proc-0.npz").exists()
    assert (tmp_path / f"ckpt-{m.step}" / "manifest.json").exists()

    m2 = _fsdp_model()
    m2.fit(x, y, batch_size=32, epochs=2, verbose=0,
           callbacks=[dtpu.callbacks.ModelCheckpoint(tmp_path, sharded=True,
                                                     restore=True)])
    # All epochs already done: restore fast-forwards, params identical.
    for a, b in zip(jax.tree_util.tree_leaves(m.params),
                    jax.tree_util.tree_leaves(m2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_restore_uncompiled_save_keeps_fresh_opt(devices, tmp_path):
    """A checkpoint saved before compile() has no optimizer leaves; restoring
    it into a compiled model must keep the fresh optimizer init (same
    contract as Checkpointer), not raise."""
    with dtpu.FullyShardedDataParallel().scope():
        m = dtpu.Model(dtpu.models.mnist_cnn())
    m.build((28, 28, 1))
    ck = dtpu.ShardedCheckpointer(tmp_path)
    ck.save(m, step=0)

    m2 = _fsdp_model()
    m2.build((28, 28, 1))
    fresh = jax.tree_util.tree_map(np.asarray, m2.opt_state)
    ck.restore_into(m2, step=0)
    for a, b in zip(jax.tree_util.tree_leaves(fresh),
                    jax.tree_util.tree_leaves(m2.opt_state)):
        np.testing.assert_array_equal(a, np.asarray(b))
    for a, b in zip(jax.tree_util.tree_leaves(m.params),
                    jax.tree_util.tree_leaves(m2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
