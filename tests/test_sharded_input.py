"""Per-host sharded input loading.

SURVEY.md §7 hard parts: the reference feeds the FULL dataset to every
worker (/root/reference/README.md:369-373); TPU-idiomatic is per-host
sharded batches with global-batch semantics unchanged. These tests pin:
shard slices assemble into exactly the unsharded batch stream (native and
Python paths), and a 2-process gang training from sharded pipelines matches
full-data feeding bit-for-bit while each process prepares only its rows.
"""

import sys
from pathlib import Path

import numpy as np
import pytest

import distributed_tpu as dtpu
from distributed_tpu.data.pipeline import Pipeline, native_available
from distributed_tpu.launch import LocalLauncher

from test_launch import write_worker

REPO = str(Path(__file__).resolve().parent.parent)


def _data(n=64, row=6, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.integers(0, 256, size=(n, row), dtype=np.uint8)
    y = rng.integers(0, 10, size=n).astype(np.int32)
    return x, y


class TestShardedPipeline:
    @pytest.mark.parametrize("use_native", [False, True], ids=["py", "native"])
    def test_shards_assemble_into_global_batch(self, use_native):
        if use_native and not native_available():
            pytest.skip("native pipeline unavailable")
        x, y = _data()
        with Pipeline(x, y, 16, seed=3, use_native=use_native) as full, \
             Pipeline(x, y, 16, seed=3, use_native=use_native,
                      shard=(0, 2)) as s0, \
             Pipeline(x, y, 16, seed=3, use_native=use_native,
                      shard=(1, 2)) as s1:
            assert s0.batch_shape == (8, 6)
            assert s0.steps_per_pass == full.steps_per_pass
            for _ in range(7):  # crosses a pass boundary (reshuffle)
                xf, yf = next(full)
                x0, y0 = next(s0)
                x1, y1 = next(s1)
                np.testing.assert_array_equal(
                    np.concatenate([x0, x1]), xf)
                np.testing.assert_array_equal(
                    np.concatenate([y0, y1]), yf)

    def test_native_matches_python_sharded(self):
        # shuffle=False: the native (splitmix64) and Python (numpy) shuffles
        # are different RNGs by design, so cross-implementation stream
        # equality only holds for the unshuffled order.
        if not native_available():
            pytest.skip("native pipeline unavailable")
        x, y = _data(48, 5, seed=1)
        with Pipeline(x, y, 12, seed=7, shard=(1, 3), shuffle=False,
                      use_native=True) as nat, \
             Pipeline(x, y, 12, seed=7, shard=(1, 3), shuffle=False,
                      use_native=False) as py:
            for _ in range(5):
                xn, yn = next(nat)
                xp, yp = next(py)
                np.testing.assert_allclose(xn, xp, rtol=1e-6)
                np.testing.assert_array_equal(yn, yp)

    def test_shard_validation(self):
        x, y = _data()
        with pytest.raises(ValueError, match="not divisible"):
            Pipeline(x, y, 16, shard=(0, 3))
        with pytest.raises(ValueError, match="shard index"):
            Pipeline(x, y, 16, shard=(2, 2))
        with pytest.raises(ValueError, match="shard index"):
            Pipeline(x, y, 16, shard=(0, 0))

    def test_seek_preserves_shard(self):
        x, y = _data()
        with Pipeline(x, y, 16, seed=5, shard=(1, 2),
                      use_native=False) as a, \
             Pipeline(x, y, 16, seed=5, shard=(1, 2),
                      use_native=False) as b:
            for _ in range(3):
                next(a)
            b.seek(3)
            xa, ya = next(a)
            xb, yb = next(b)
            np.testing.assert_array_equal(xa, xb)
            np.testing.assert_array_equal(ya, yb)


class TestPerHostPlacement:
    def test_put_batch_per_host_single_process(self, devices):
        # Single process: per_host input == the full batch; placement must
        # equal the host-global path exactly.
        strategy = dtpu.DataParallel()
        x = np.arange(32, dtype=np.float32).reshape(8, 4)
        a = strategy.put_batch({"x": x})["x"]
        b = strategy.put_batch({"x": x}, per_host=True)["x"]
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert b.sharding.spec == a.sharding.spec


@pytest.mark.slow
def test_two_process_sharded_training_bit_identical(tmp_path):
    """Each process feeds ONLY its pipeline shard; the run must match
    full-data feeding bit-for-bit (same loss stream), and each process's
    pipeline must emit only shard-sized arrays."""
    body = """
    import os
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import distributed_tpu as dtpu
    from distributed_tpu.data.pipeline import Pipeline
    from distributed_tpu.launch import report_result

    spec = dtpu.cluster.initialize()
    rng = np.random.default_rng(0)
    x = rng.integers(0, 256, size=(256, 28, 28, 1), dtype=np.uint8)
    y = rng.integers(0, 10, size=256).astype(np.int32)

    strategy = dtpu.DataParallel()
    with strategy.scope():
        m = dtpu.Model(dtpu.models.mnist_cnn())
        m.compile(optimizer=dtpu.optim.SGD(0.05), metrics=["accuracy"])
    m.build((28, 28, 1))

    GB = 64
    with Pipeline(x, y, GB, seed=4, use_native=False,
                  shard=(spec.index, spec.num_processes)) as p:
        assert p.batch_shape[0] == GB // spec.num_processes
        hist = m.fit(p, epochs=2, steps_per_epoch=3, verbose=0)

    # Reference run: full-data feeding through an UNSHARDED pipeline on
    # every process (the round-1 behavior).
    with strategy.scope():
        m2 = dtpu.Model(dtpu.models.mnist_cnn())
        m2.compile(optimizer=dtpu.optim.SGD(0.05), metrics=["accuracy"])
    m2.build((28, 28, 1))
    with Pipeline(x, y, GB, seed=4, use_native=False) as pfull:
        hist2 = m2.fit(pfull, epochs=2, steps_per_epoch=3, verbose=0)

    report_result({"rank": spec.index,
                   "loss": hist.metrics["loss"],
                   "loss_full": hist2.metrics["loss"]})
    """
    script = write_worker(tmp_path, body)
    results = LocalLauncher().run([sys.executable, script], 2, timeout=300)
    assert all(r.ok for r in results), [
        (r.index, r.error, r.log_tail[-500:]) for r in results
    ]
    for r in results:
        assert r.value["loss"] == r.value["loss_full"], r.value
    # and both processes saw identical (replicated) metrics
    assert results[0].value["loss"] == results[1].value["loss"]


class TestPerHostGuards:
    def test_single_device_rejects_per_host(self):
        strategy = dtpu.SingleDevice()
        with pytest.raises(ValueError, match="per-host|fraction"):
            strategy.put_batch({"x": np.zeros((4, 2), np.float32)},
                               per_host=True)

    def test_fit_with_sharded_pipeline_no_strategy_fails_loudly(self):
        x, y = _data(64, 6)
        m = dtpu.Model(dtpu.nn.Sequential(
            [dtpu.nn.Dense(16, activation="relu"), dtpu.nn.Dense(10)]))
        m.compile(optimizer=dtpu.optim.SGD(0.1),
                  loss="sparse_categorical_crossentropy")
        m.build((6,))
        with Pipeline(x, y, 16, shard=(0, 2), use_native=False) as p:
            with pytest.raises(ValueError, match="fraction|per-host"):
                m.fit(p, epochs=1, verbose=0)
