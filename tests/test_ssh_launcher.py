"""SSHLauncher behavior without real hosts: a fake-ssh shim runs the
"remote" command locally with bash, exercising the production multi-host
path — stdout result framing, peer-failure gang kill, timeout labeling,
and config injection (the reference's per-machine manual sessions,
/root/reference/README.md:82-114, automated)."""

import json
import os
import stat
import sys
import time

import pytest

from distributed_tpu.cluster import config as config_lib
from distributed_tpu.launch.core import SSHLauncher, STDOUT_MARK


@pytest.fixture()
def fake_ssh(tmp_path):
    """An ssh stand-in: drops the host argument, runs the command locally."""
    path = tmp_path / "fake-ssh"
    path.write_text('#!/bin/sh\nshift\nexec bash -c "$1"\n')
    path.chmod(path.stat().st_mode | stat.S_IXUSR)
    return str(path)


def _worker_script(tmp_path, body):
    script = tmp_path / "worker.py"
    script.write_text(body)
    return str(script)


def test_result_framing_and_config_injection(tmp_path, fake_ssh):
    script = _worker_script(
        tmp_path,
        "import os, json\n"
        "from distributed_tpu.cluster import from_env\n"
        "from distributed_tpu.launch import report_result\n"
        "spec = from_env()\n"
        # noise around the frame must not confuse the parser
        "print('log line before')\n"
        "report_result({'rank': spec.index, 'peers': spec.workers})\n"
        "print('log line after')\n",
    )
    hosts = ["127.0.0.1", "127.0.0.1"]
    launcher = SSHLauncher(hosts, ssh_cmd=fake_ssh)
    results = launcher.run(
        [sys.executable, script], timeout=60,
        env_extra={"PYTHONPATH": os.pathsep.join(sys.path)},
    )
    assert [r.index for r in results] == [0, 1]
    assert all(r.ok for r in results), results
    assert sorted(r.value["rank"] for r in results) == [0, 1]
    peer_lists = {tuple(r.value["peers"]) for r in results}
    assert len(peer_lists) == 1  # same rank-ordered list everywhere
    assert all(len(r.value["peers"]) == 2 for r in results)


def test_malformed_frame_is_ignored(tmp_path, fake_ssh):
    script = _worker_script(
        tmp_path,
        f"print({STDOUT_MARK!r} + 'not json')\n",
    )
    launcher = SSHLauncher(["127.0.0.1"], ssh_cmd=fake_ssh)
    results = launcher.run([sys.executable, script], timeout=60)
    assert results[0].ok
    assert results[0].value is None


def test_peer_failure_gang_kill(tmp_path, fake_ssh):
    script = _worker_script(
        tmp_path,
        "import os, sys, time, json\n"
        "spec = json.loads(os.environ['DTPU_CONFIG'])\n"
        "if spec['task']['index'] == 1:\n"
        "    sys.exit(3)\n"
        "time.sleep(300)\n",
    )
    launcher = SSHLauncher(["127.0.0.1", "127.0.0.1"], ssh_cmd=fake_ssh)
    t0 = time.time()
    results = launcher.run([sys.executable, script], timeout=240, grace=2)
    elapsed = time.time() - t0
    assert elapsed < 60, "gang kill must not wait out the timeout"
    by_rank = {r.index: r for r in results}
    assert not by_rank[1].ok and "exit code 3" in by_rank[1].error
    assert not by_rank[0].ok
    assert "peer failure" in by_rank[0].error
    # the killed worker's log is preserved for debugging
    assert by_rank[0].exit_code != 0


def test_timeout_labeling(tmp_path, fake_ssh):
    script = _worker_script(tmp_path, "import time\ntime.sleep(300)\n")
    launcher = SSHLauncher(["127.0.0.1"], ssh_cmd=fake_ssh)
    t0 = time.time()
    results = launcher.run([sys.executable, script], timeout=3, grace=2)
    assert time.time() - t0 < 60
    assert not results[0].ok
    assert results[0].error == "timeout"


def test_preflight_failure_raises(fake_ssh):
    # An unresolvable host must fail fast, before any spawn.
    launcher = SSHLauncher(
        ["definitely-not-a-real-host.invalid"], ssh_cmd=fake_ssh
    )
    with pytest.raises(RuntimeError, match="Preflight"):
        launcher.run([sys.executable, "-c", "pass"], timeout=10)
