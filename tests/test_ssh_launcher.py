"""SSHLauncher behavior without real hosts: a fake-ssh shim runs the
"remote" command locally with bash, exercising the production multi-host
path — stdout result framing, peer-failure gang kill, timeout labeling,
and config injection (the reference's per-machine manual sessions,
/root/reference/README.md:82-114, automated)."""

import json
import os
import stat
import sys
import time

import pytest

from distributed_tpu.cluster import config as config_lib
from distributed_tpu.launch.core import (
    HEARTBEAT_MARK,
    PID_MARK,
    SSHLauncher,
    STDOUT_MARK,
)


@pytest.fixture()
def fake_ssh(tmp_path):
    """An ssh stand-in: drops the host argument, runs the command locally.
    Every executed remote command is appended to fake-ssh.log so tests
    can assert WHICH commands the launcher issued (e.g. remote kills)."""
    path = tmp_path / "fake-ssh"
    log = tmp_path / "fake-ssh.log"
    path.write_text(
        "#!/bin/sh\n"
        "shift\n"
        f'printf \'%s\\n\' "$1" >> {log}\n'
        'exec bash -c "$1"\n'
    )
    path.chmod(path.stat().st_mode | stat.S_IXUSR)
    return str(path)


def _worker_script(tmp_path, body):
    script = tmp_path / "worker.py"
    script.write_text(body)
    return str(script)


def test_result_framing_and_config_injection(tmp_path, fake_ssh):
    script = _worker_script(
        tmp_path,
        "import os, json\n"
        "from distributed_tpu.cluster import from_env\n"
        "from distributed_tpu.launch import report_result\n"
        "spec = from_env()\n"
        # noise around the frame must not confuse the parser
        "print('log line before')\n"
        "report_result({'rank': spec.index, 'peers': spec.workers})\n"
        "print('log line after')\n",
    )
    hosts = ["127.0.0.1", "127.0.0.1"]
    launcher = SSHLauncher(hosts, ssh_cmd=fake_ssh)
    results = launcher.run(
        [sys.executable, script], timeout=60,
        env_extra={"PYTHONPATH": os.pathsep.join(sys.path)},
    )
    assert [r.index for r in results] == [0, 1]
    assert all(r.ok for r in results), results
    assert sorted(r.value["rank"] for r in results) == [0, 1]
    peer_lists = {tuple(r.value["peers"]) for r in results}
    assert len(peer_lists) == 1  # same rank-ordered list everywhere
    assert all(len(r.value["peers"]) == 2 for r in results)


def test_malformed_frame_is_ignored(tmp_path, fake_ssh):
    script = _worker_script(
        tmp_path,
        f"print({STDOUT_MARK!r} + 'not json')\n",
    )
    launcher = SSHLauncher(["127.0.0.1"], ssh_cmd=fake_ssh)
    results = launcher.run([sys.executable, script], timeout=60)
    assert results[0].ok
    assert results[0].value is None


def test_peer_failure_gang_kill(tmp_path, fake_ssh):
    script = _worker_script(
        tmp_path,
        "import os, sys, time, json\n"
        "spec = json.loads(os.environ['DTPU_CONFIG'])\n"
        "if spec['task']['index'] == 1:\n"
        "    sys.exit(3)\n"
        "time.sleep(300)\n",
    )
    launcher = SSHLauncher(["127.0.0.1", "127.0.0.1"], ssh_cmd=fake_ssh)
    t0 = time.time()
    results = launcher.run([sys.executable, script], timeout=240, grace=2)
    elapsed = time.time() - t0
    assert elapsed < 60, "gang kill must not wait out the timeout"
    by_rank = {r.index: r for r in results}
    assert not by_rank[1].ok and "exit code 3" in by_rank[1].error
    assert not by_rank[0].ok
    assert "peer failure" in by_rank[0].error
    # the killed worker's log is preserved for debugging
    assert by_rank[0].exit_code != 0


def test_timeout_labeling(tmp_path, fake_ssh):
    script = _worker_script(tmp_path, "import time\ntime.sleep(300)\n")
    launcher = SSHLauncher(["127.0.0.1"], ssh_cmd=fake_ssh)
    t0 = time.time()
    results = launcher.run([sys.executable, script], timeout=3, grace=2)
    assert time.time() - t0 < 60
    assert not results[0].ok
    assert results[0].error == "timeout"


# @slow (tier-1 budget, PR 10): 10s; the liveness-timeout mechanism
# is pinned in-tier by test_launch.py's local variant.
@pytest.mark.slow
def test_liveness_timeout_over_ssh(tmp_path, fake_ssh):
    """The ssh liveness transport end-to-end: heartbeats ride stdout
    marks, a SIGSTOPped worker's stalled beat is detected within
    liveness_timeout, the REMOTE pid (announced via the exec/$$ framing)
    is killed — fake-ssh executes the `kill -9 <pid>` like a real remote
    would — and the survivor is gang-killed within grace."""
    script = _worker_script(
        tmp_path,
        "import os, json, signal, time\n"
        "from distributed_tpu.launch import heartbeat, report_result\n"
        "spec = json.loads(os.environ['DTPU_CONFIG'])\n"
        "for i in range(400):\n"
        "    heartbeat(min_interval=0.0)\n"
        "    time.sleep(0.05)\n"
        "    if spec['task']['index'] == 1 and i == 8:\n"
        "        signal.raise_signal(signal.SIGSTOP)\n"
        "report_result({'rank': spec['task']['index']})\n",
    )
    launcher = SSHLauncher(["127.0.0.1", "127.0.0.1"], ssh_cmd=fake_ssh)
    t0 = time.time()
    results = launcher.run(
        [sys.executable, script], timeout=300, grace=3.0,
        liveness_timeout=5.0,  # beats every 0.05s; 5s absorbs CI stalls
        env_extra={"PYTHONPATH": os.pathsep.join(sys.path)},
    )
    elapsed = time.time() - t0
    by_rank = {r.index: r for r in results}
    assert not by_rank[1].ok
    assert "liveness timeout" in by_rank[1].error, by_rank[1].error
    assert not by_rank[0].ok
    assert "peer failure" in by_rank[0].error, by_rank[0].error
    # Detection rode the heartbeat, not the 300s run timeout.
    assert elapsed < 90, elapsed
    # The launcher really issued the REMOTE kill for the hung worker's
    # announced pid (under fake-ssh, p.kill() alone would also pass the
    # row asserts — the command log pins the remote-kill path).
    import re

    log_text = (tmp_path / "fake-ssh.log").read_text()
    assert re.search(r"^kill -9 \d+$", log_text, re.M), log_text[-500:]


def test_heartbeat_marks_do_not_pollute_output(tmp_path, fake_ssh):
    """Heartbeat/PID marker lines are consumed by the drain — result
    parsing and log tails never see them."""
    script = _worker_script(
        tmp_path,
        "from distributed_tpu.launch import heartbeat, report_result\n"
        "heartbeat(min_interval=0.0)\n"
        "print('real log line')\n"
        "heartbeat(min_interval=0.0)\n"
        "report_result({'ok': True})\n"
        "raise SystemExit(5)\n",  # nonzero so log_tail is captured
    )
    launcher = SSHLauncher(["127.0.0.1"], ssh_cmd=fake_ssh)
    results = launcher.run(
        [sys.executable, script], timeout=60,
        env_extra={"PYTHONPATH": os.pathsep.join(sys.path)},
    )
    (r,) = results
    assert r.value == {"ok": True}
    assert HEARTBEAT_MARK not in r.log_tail
    assert PID_MARK not in r.log_tail
    assert "real log line" in r.log_tail


def test_preflight_failure_raises(fake_ssh):
    # An unresolvable host must fail fast, before any spawn.
    launcher = SSHLauncher(
        ["definitely-not-a-real-host.invalid"], ssh_cmd=fake_ssh
    )
    with pytest.raises(RuntimeError, match="Preflight"):
        launcher.run([sys.executable, "-c", "pass"], timeout=10)
