"""Replica-sync checking: the reference's identical-metrics invariant
(/root/reference/README.md:226-232) as a callable assertion."""

import jax
import numpy as np
import pytest

import distributed_tpu as dtpu
from distributed_tpu.utils import assert_replicas_identical, replica_drift


def _dp_model():
    strategy = dtpu.DataParallel()
    with strategy.scope():
        m = dtpu.Model(dtpu.models.mnist_cnn())
        m.compile(optimizer=dtpu.optim.SGD(0.05),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    x, y = dtpu.data.synthetic_images(64, (28, 28), 10, 0)
    x = x[..., None].astype(np.float32) / 255.0
    m.fit(x, y.astype(np.int32), batch_size=64, epochs=1,
          steps_per_epoch=2, verbose=0, seed=0)
    return m


def test_healthy_dp_run_passes_and_reports_zero_drift():
    m = _dp_model()
    assert_replicas_identical(m.params)
    drift = replica_drift(m.params)
    assert drift, "expected replicated params to be checked"
    assert all(v == 0.0 for v in drift.values()), drift


@pytest.mark.smoke
def test_diverged_replica_is_caught():
    m = _dp_model()
    # Corrupt one device's replica of one parameter.
    leaf = m.params["dense"]["bias"]
    shards = list(leaf.addressable_shards)
    per_device = [np.asarray(s.data) for s in shards]
    per_device[1] = per_device[1] + 1.0
    bufs = [jax.device_put(a, s.device)
            for a, s in zip(per_device, shards)]
    bad = jax.make_array_from_single_device_arrays(
        leaf.shape, leaf.sharding, bufs)
    m.params["dense"]["bias"] = bad
    with pytest.raises(AssertionError, match="dense.*bias"):
        assert_replicas_identical(m.params)
    drift = replica_drift(m.params)
    assert max(drift.values()) >= 1.0


def test_unsharded_arrays_are_ignored():
    params = {"w": np.ones((4,), np.float32)}
    assert replica_drift(params) == {}
    assert_replicas_identical(params)  # no-op, no raise


def test_sync_check_callback_passes_on_healthy_run_and_validates():
    SyncCheck = dtpu.callbacks.SyncCheck

    strategy = dtpu.DataParallel()
    with strategy.scope():
        m = dtpu.Model(dtpu.models.mnist_cnn())
        m.compile(optimizer=dtpu.optim.SGD(0.05),
                  loss="sparse_categorical_crossentropy")
    x, y = dtpu.data.synthetic_images(64, (28, 28), 10, 0)
    x = x[..., None].astype(np.float32) / 255.0
    h = m.fit(x, y.astype(np.int32), batch_size=64, epochs=2,
              steps_per_epoch=2, verbose=0, seed=0,
              callbacks=[SyncCheck(every=1, include_opt_state=True)])
    assert np.isfinite(h.history["loss"]).all()
    with pytest.raises(ValueError):
        SyncCheck(every=0)


# @slow (tier-1 budget, PR 17): ~9s subprocess launcher drive; the
# in-process divergence tests (diverged_replica_is_caught, healthy-run
# zero-drift) stay in-tier and pin the same detector.
@pytest.mark.slow
def test_cross_host_divergence_caught_via_launcher(tmp_path):
    """2-process gang (1 CPU device each): the local replica check has
    nothing to compare, so only the cross-host fingerprint path can catch
    rank-1 perturbing its weights after training."""
    import subprocess  # noqa: F401 (parity with test_launch style)
    import sys
    import textwrap
    from pathlib import Path

    from distributed_tpu.launch import LocalLauncher

    repo = str(Path(__file__).resolve().parent.parent)
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent("""
        import os, sys
        sys.path.insert(0, __REPO__)
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        import jax
        jax.config.update("jax_platforms", "cpu")
        import numpy as np
        import distributed_tpu as dtpu
        from distributed_tpu.launch import report_result
        from distributed_tpu.utils import assert_replicas_identical

        spec = dtpu.cluster.initialize()
        x, y = dtpu.data.synthetic_images(64, (28, 28), 10, 0)
        x = x[..., None].astype(np.float32) / 255.0
        strategy = dtpu.DataParallel()
        with strategy.scope():
            m = dtpu.Model(dtpu.models.mnist_cnn())
            m.compile(optimizer=dtpu.optim.SGD(0.05), metrics=["accuracy"])
        m.fit(x, y.astype(np.int32), batch_size=64, epochs=1,
              steps_per_epoch=2, verbose=0, seed=0)

        assert_replicas_identical(m.params)  # healthy: must pass

        # Rank 1 corrupts one weight via a purely process-local
        # reconstruction (a device_put onto the cross-process sharding
        # would itself be a collective and desync the gang).
        if spec.index == 1:
            leaf = m.params["dense"]["bias"]
            shard = leaf.addressable_shards[0]
            buf = jax.device_put(np.asarray(shard.data) + 1.0, shard.device)
            m.params["dense"]["bias"] = (
                jax.make_array_from_single_device_arrays(
                    leaf.shape, leaf.sharding, [buf]))
        try:
            assert_replicas_identical(m.params)
            report_result({"caught": False})
        except AssertionError as e:
            report_result({"caught": True, "msg": str(e)[:120]})
    """).replace("__REPO__", repr(repo)))
    results = LocalLauncher().run([sys.executable, str(script)], 2,
                                  timeout=300)
    assert all(r.ok for r in results), [
        (r.index, r.error, r.log_tail[-400:]) for r in results
    ]
    for r in results:
        assert r.value["caught"], r.value
        assert "dense" in r.value["msg"]
