"""Replica-sync checking: the reference's identical-metrics invariant
(/root/reference/README.md:226-232) as a callable assertion."""

import jax
import numpy as np
import pytest

import distributed_tpu as dtpu
from distributed_tpu.utils import assert_replicas_identical, replica_drift


def _dp_model():
    strategy = dtpu.DataParallel()
    with strategy.scope():
        m = dtpu.Model(dtpu.models.mnist_cnn())
        m.compile(optimizer=dtpu.optim.SGD(0.05),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    x, y = dtpu.data.synthetic_images(64, (28, 28), 10, 0)
    x = x[..., None].astype(np.float32) / 255.0
    m.fit(x, y.astype(np.int32), batch_size=64, epochs=1,
          steps_per_epoch=2, verbose=0, seed=0)
    return m


def test_healthy_dp_run_passes_and_reports_zero_drift():
    m = _dp_model()
    assert_replicas_identical(m.params)
    drift = replica_drift(m.params)
    assert drift, "expected replicated params to be checked"
    assert all(v == 0.0 for v in drift.values()), drift


def test_diverged_replica_is_caught():
    m = _dp_model()
    # Corrupt one device's replica of one parameter.
    leaf = m.params["dense"]["bias"]
    shards = list(leaf.addressable_shards)
    per_device = [np.asarray(s.data) for s in shards]
    per_device[1] = per_device[1] + 1.0
    bufs = [jax.device_put(a, s.device)
            for a, s in zip(per_device, shards)]
    bad = jax.make_array_from_single_device_arrays(
        leaf.shape, leaf.sharding, bufs)
    m.params["dense"]["bias"] = bad
    with pytest.raises(AssertionError, match="dense.*bias"):
        assert_replicas_identical(m.params)
    drift = replica_drift(m.params)
    assert max(drift.values()) >= 1.0


def test_unsharded_arrays_are_ignored():
    params = {"w": np.ones((4,), np.float32)}
    assert replica_drift(params) == {}
    assert_replicas_identical(params)  # no-op, no raise


def test_sync_check_callback_passes_on_healthy_run_and_validates():
    SyncCheck = dtpu.callbacks.SyncCheck

    strategy = dtpu.DataParallel()
    with strategy.scope():
        m = dtpu.Model(dtpu.models.mnist_cnn())
        m.compile(optimizer=dtpu.optim.SGD(0.05),
                  loss="sparse_categorical_crossentropy")
    x, y = dtpu.data.synthetic_images(64, (28, 28), 10, 0)
    x = x[..., None].astype(np.float32) / 255.0
    h = m.fit(x, y.astype(np.int32), batch_size=64, epochs=2,
              steps_per_epoch=2, verbose=0, seed=0,
              callbacks=[SyncCheck(every=1, include_opt_state=True)])
    assert np.isfinite(h.history["loss"]).all()
    with pytest.raises(ValueError):
        SyncCheck(every=0)
