"""Transformer LM + tensor parallelism.

Beyond-reference capability (the reference has no attention, SURVEY.md §2c):
decoder-only LM built from the framework's own primitives, and Megatron-style
tensor sharding over the 'model' mesh axis via layer hints, validated on the
8-device CPU sim (data x model = 4 x 2).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec

import distributed_tpu as dtpu
from distributed_tpu import nn

VOCAB = 64


def _lm(max_len=16, **kw):
    kw.setdefault("num_layers", 2)
    kw.setdefault("d_model", 32)
    kw.setdefault("num_heads", 4)
    return dtpu.models.transformer_lm(VOCAB, max_len=max_len, **kw)


def _copy_task(n, t, seed=0):
    """Next-token-predictable data: a fixed cyclic sequence per start token."""
    rng = np.random.default_rng(seed)
    starts = rng.integers(0, VOCAB, size=n)
    pos = np.arange(t + 1)[None, :]
    toks = (starts[:, None] + pos) % VOCAB
    return toks[:, :-1].astype(np.int32), toks[:, 1:].astype(np.int32)


class TestAttention:
    @pytest.mark.smoke
    def test_forward_shape(self):
        layer = nn.MultiHeadAttention(4)
        params, state, out = layer.init(jax.random.PRNGKey(0), (10, 32))
        assert out == (10, 32)
        y, _ = layer.apply(params, state, jnp.zeros((2, 10, 32)))
        assert y.shape == (2, 10, 32)

    def test_head_divisibility(self):
        with pytest.raises(ValueError, match="divisible"):
            nn.MultiHeadAttention(5).init(jax.random.PRNGKey(0), (10, 32))

    def test_causality(self):
        module = _lm()
        params, state, _ = module.init(jax.random.PRNGKey(0), (8,))
        x1 = jnp.zeros((1, 8), jnp.int32)
        x2 = x1.at[0, 5].set(7)  # change a future token
        l1, _ = module.apply(params, state, x1)
        l2, _ = module.apply(params, state, x2)
        # positions < 5 must be unaffected; position >= 5 must differ
        np.testing.assert_allclose(l1[0, :5], l2[0, :5], atol=1e-6)
        assert not np.allclose(l1[0, 5:], l2[0, 5:])

    def test_noncausal_attends_everywhere(self):
        layer = nn.MultiHeadAttention(2, causal=False)
        params, state, _ = layer.init(jax.random.PRNGKey(0), (6, 16))
        x = jax.random.normal(jax.random.PRNGKey(1), (1, 6, 16))
        y1, _ = layer.apply(params, state, x)
        y2, _ = layer.apply(params, state, x.at[0, 5].set(0.0))
        assert not np.allclose(y1[0, 0], y2[0, 0])  # pos 0 sees pos 5

    def test_positional_embedding_max_len(self):
        with pytest.raises(ValueError, match="max_len"):
            nn.PositionalEmbedding(4).init(jax.random.PRNGKey(0), (8, 16))


class TestTransformerTraining:
    def test_learns_copy_task(self):
        model = dtpu.Model(_lm())
        model.compile(optimizer=dtpu.optim.Adam(1e-2),
                      loss="sparse_categorical_crossentropy",
                      metrics=["accuracy"])
        x, y = _copy_task(256, 16)
        hist = model.fit(x, y, batch_size=64, epochs=10, verbose=0, seed=1)
        assert hist.history["accuracy"][-1] > 0.8, hist.history

    def test_pallas_loss_path(self):
        model = dtpu.Model(_lm(num_layers=1))
        model.compile(optimizer=dtpu.optim.Adam(1e-2),
                      loss="pallas_sparse_categorical_crossentropy")
        x, y = _copy_task(128, 16)
        hist = model.fit(x, y, batch_size=64, epochs=2, verbose=0)
        assert hist.history["loss"][-1] < hist.history["loss"][0]


class TestTensorParallel:
    def test_param_shardings(self, devices):
        strategy = dtpu.DataTensorParallel(model_parallel=2)
        with strategy.scope():
            model = dtpu.Model(_lm())
            model.compile(optimizer=dtpu.optim.SGD(0.1),
                          loss="sparse_categorical_crossentropy")
        model.build((16,))
        # find an attention block and the MLP denses
        p = model.params
        attn = p["residual"]["main"]["multi_head_attention"]
        assert attn["wq"].sharding.spec == PartitionSpec(None, "model")
        assert attn["wo"].sharding.spec == PartitionSpec("model", None)
        mlp = p["residual_1"]["main"]
        assert mlp["dense"]["kernel"].sharding.spec == PartitionSpec(None, "model")
        assert mlp["dense"]["bias"].sharding.spec == PartitionSpec("model")
        assert mlp["dense_1"]["kernel"].sharding.spec == PartitionSpec("model", None)
        # unhinted params stay replicated
        emb = p["embedding"]["table"]
        assert emb.sharding.spec == PartitionSpec()
        # optimizer state shards like the params (momentum mirrors kernel);
        # named optimizers wrap the inner state in inject_hyperparams.
        model.compile(optimizer=dtpu.optim.SGD(0.1, momentum=0.9),
                      loss="sparse_categorical_crossentropy")
        mom = model.opt_state.inner_state[0].trace["residual"]["main"][
            "multi_head_attention"]["wq"]
        assert mom.sharding.spec == PartitionSpec(None, "model")

    def test_tp_matches_single_device(self, devices):
        x, y = _copy_task(64, 16, seed=3)

        def train(strategy):
            if strategy is None:
                model = dtpu.Model(_lm())
                model.compile(optimizer=dtpu.optim.SGD(0.1),
                              loss="sparse_categorical_crossentropy",
                              metrics=["accuracy"])
            else:
                with strategy.scope():
                    model = dtpu.Model(_lm())
                    model.compile(optimizer=dtpu.optim.SGD(0.1),
                                  loss="sparse_categorical_crossentropy",
                                  metrics=["accuracy"])
            hist = model.fit(x, y, batch_size=32, epochs=2, verbose=0,
                             seed=7, shuffle=False)
            return hist.history["loss"]

        ref = train(None)
        tp = train(dtpu.DataTensorParallel(model_parallel=2))
        np.testing.assert_allclose(ref, tp, rtol=2e-4, atol=2e-5)

    def test_divisibility_check(self, devices):
        with pytest.raises(ValueError, match="divisible"):
            dtpu.DataTensorParallel(model_parallel=3)
