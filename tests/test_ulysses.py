"""Ulysses (all-to-all) sequence parallelism: DataSeqParallel(attention=
"ulysses").

Same capability surface as ring attention (tests/test_ring_attention.py)
via a different collective pattern: two all-to-alls reshard tokens->heads
so each device runs full-T attention on H/n heads. Parity requirement:
identical training trajectories to single-device dense, and the compiled
HLO actually contains the all-to-alls (otherwise GSPMD silently
all-gathered instead).
"""

import jax
import numpy as np
import pytest

import distributed_tpu as dtpu


def _data(vocab=32, n=64, t=16):
    rng = np.random.default_rng(0)
    starts = rng.integers(0, vocab, size=n)
    toks = (starts[:, None] + np.arange(t + 1)[None]) % vocab
    return toks[:, :-1].astype(np.int32), toks[:, 1:].astype(np.int32)


def _train(strategy, x, y, num_heads=4):
    def build():
        m = dtpu.Model(
            dtpu.models.transformer_lm(
                32, num_layers=1, d_model=32, num_heads=num_heads, max_len=16
            )
        )
        m.compile(optimizer=dtpu.optim.SGD(0.1),
                  loss="sparse_categorical_crossentropy")
        return m

    if strategy is None:
        model = build()
    else:
        with strategy.scope():
            model = build()
    hist = model.fit(x, y, batch_size=32, epochs=2, verbose=0, seed=4,
                     shuffle=False)
    return model, hist.history["loss"]


def test_invalid_attention_mode_raises(devices):
    with pytest.raises(ValueError, match="ring.*ulysses|ulysses.*ring"):
        dtpu.DataSeqParallel(seq_parallel=2, attention="flash")


def test_lm_trains_and_matches_dense(devices):
    x, y = _data()
    _, ref = _train(None, x, y)
    _, ul = _train(
        dtpu.DataSeqParallel(seq_parallel=4, attention="ulysses"), x, y
    )
    np.testing.assert_allclose(ref, ul, rtol=2e-4, atol=2e-5)


# @slow (tier-1 budget, PR 12): 11s, and transitively covered in-tier —
# ulysses==dense (above) and ring==dense (test_ring_attention) both stay;
# run with -m slow when touching either attention path.
@pytest.mark.slow
def test_ulysses_equals_ring(devices):
    x, y = _data()
    _, ring = _train(dtpu.DataSeqParallel(seq_parallel=4), x, y)
    _, ul = _train(
        dtpu.DataSeqParallel(seq_parallel=4, attention="ulysses"), x, y
    )
    np.testing.assert_allclose(ring, ul, rtol=2e-4, atol=2e-5)


def test_compiled_step_contains_all_to_all(devices):
    strategy = dtpu.DataSeqParallel(seq_parallel=4, attention="ulysses")
    with strategy.scope():
        m = dtpu.Model(
            dtpu.models.transformer_lm(
                32, num_layers=1, d_model=32, num_heads=4, max_len=16
            )
        )
        m.compile(optimizer=dtpu.optim.SGD(0.1),
                  loss="sparse_categorical_crossentropy")
    m.build((16,))
    batch = strategy.put_batch({
        "x": np.zeros((8, 16), np.int32), "y": np.zeros((8, 16), np.int32)
    })
    module, state = m.module, m.state
    fwd = jax.jit(lambda p, xx: module.apply(p, state, xx, train=False)[0])
    with strategy.scope():  # trace-time detection reads the ambient strategy
        hlo = fwd.lower(m.params, batch["x"]).compile().as_text()
    assert "all-to-all" in hlo, (
        "Ulysses resharding did not lower to all-to-all"
    )


def test_indivisible_heads_raise(devices):
    x, y = _data()
    with pytest.raises(ValueError, match="divisible"):
        _train(
            dtpu.DataSeqParallel(seq_parallel=4, attention="ulysses"),
            x, y, num_heads=2,
        )


# @slow (tier-1 budget, PR 10): 10s long-context compile; the
# ulysses==ring and trains-matches-dense parity pins stay in-tier.
@pytest.mark.slow
def test_long_context_ulysses_flash_no_quadratic_buffer(devices):
    """VERDICT r2 item 5: per-head-shard Ulysses attention must be O(T)
    memory — numerics match ring attention AND the compiled forward holds
    no (T, T) f32 score buffer (dense per-shard scores would reintroduce
    the O(T^2) the seq axis removed)."""
    import re

    t, vocab = 512, 32
    rng = np.random.default_rng(1)
    toks = rng.integers(0, vocab, (4, t + 1)).astype(np.int32)
    x, y = toks[:, :-1], toks[:, 1:]

    def step(strategy):
        with strategy.scope():
            m = dtpu.Model(
                dtpu.models.transformer_lm(
                    vocab, num_layers=1, d_model=32, num_heads=4, max_len=t,
                    flash=True,  # 'auto' only picks flash on a TPU backend
                )
            )
            m.compile(optimizer=dtpu.optim.SGD(0.1),
                      loss="sparse_categorical_crossentropy")
        m.fit(x, y, batch_size=4, epochs=1, steps_per_epoch=1, verbose=0,
              shuffle=False)
        return m

    ring = step(dtpu.DataSeqParallel(seq_parallel=4, attention="ring"))
    ul_s = dtpu.DataSeqParallel(seq_parallel=4, attention="ulysses")
    ul = step(ul_s)
    for a, b in zip(jax.tree_util.tree_leaves(ring.params),
                    jax.tree_util.tree_leaves(ul.params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5
        )

    batch = ul_s.put_batch({"x": x})
    module, state = ul.module, ul.state
    fwd = jax.jit(lambda p, xx: module.apply(p, state, xx, train=False)[0])
    with ul_s.scope():
        hlo = fwd.lower(ul.params, batch["x"]).compile().as_text()
    quad = re.findall(r"f32\[[0-9]+(?:,[0-9]+)*,512,512\]", hlo)
    assert not quad, f"quadratic score buffers in HLO: {set(quad)}"
    assert "all-to-all" in hlo
