"""ZeRO-sharded data parallelism: ZeRO-1 / FSDP / grad accumulation.

Parity contract (ISSUE 4): ``ZeroDataParallel`` and ``FSDP`` change WHERE
model state lives, never what gets computed — per-step losses must match
plain ``DataParallel`` on the same batches; and ``fit(grad_accum=M)`` must
take the same optimizer trajectory as the equivalent M-times-bigger batch.
All on a 2-device slice of the 8-device CPU sim, small and short: the
tier-1 budget has ~30s of headroom total.
"""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec

import distributed_tpu as dtpu


def _data(n=128):
    x, y = dtpu.data.synthetic_images(n, (28, 28), 10, seed=11)
    return x[..., None].astype(np.float32) / 255.0, y.astype(np.int32)


def _model(strategy, **compile_kw):
    with strategy.scope():
        m = dtpu.Model(dtpu.models.mnist_cnn())
        m.compile(optimizer=dtpu.optim.Adam(1e-3),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"], **compile_kw)
    return m


def _step_losses(model, x, y, steps, batch=32, **fit_kw):
    """Per-optimizer-step losses via the on_batch_end log (a device scalar;
    float() syncs once per step — 10 tiny steps, cheap)."""
    losses = []
    cb = dtpu.callbacks.LambdaCallback(
        on_batch_end=lambda m, s, logs: losses.append(float(logs["loss"]))
    )
    model.fit(x, y, batch_size=batch, epochs=1, steps_per_epoch=steps,
              verbose=0, seed=5, shuffle=False, callbacks=[cb], **fit_kw)
    return losses


@pytest.fixture(scope="module")
def two_dev(devices):
    return devices[:2]


@pytest.fixture(scope="module")
def dp_run(two_dev):
    """Reference DataParallel run shared by the parity tests: per-step
    losses over 10 steps plus the fit telemetry (memory accounting)."""
    x, y = _data()
    m = _model(dtpu.DataParallel(devices=two_dev))
    losses = _step_losses(m, x, y, steps=10)
    return {"losses": losses, "telemetry": m.last_fit_telemetry,
            "x": x, "y": y}


class TestZero1:
    def test_opt_state_sharded_params_replicated(self, two_dev):
        strategy = dtpu.ZeroDataParallel(devices=two_dev)
        m = _model(strategy)
        m.build((28, 28, 1))
        assert m.params["dense"]["kernel"].sharding.spec == PartitionSpec()
        mu = m.opt_state.inner_state[0].mu["dense"]["kernel"]
        nu = m.opt_state.inner_state[0].nu["dense"]["kernel"]
        assert mu.sharding.spec == PartitionSpec("data", None)
        assert nu.sharding.spec == PartitionSpec("data", None)
        # each device holds half the rows of every Adam moment
        shapes = {s.data.shape for s in mu.addressable_shards}
        assert shapes == {(mu.shape[0] // 2, mu.shape[1])}
        # scalars (inject_hyperparams' learning_rate, the step count) and
        # indivisible shapes replicate
        lr = dtpu.optim.get_hyperparam(m.opt_state, "learning_rate")
        assert lr.sharding.spec == PartitionSpec()

    def test_matches_dp(self, dp_run, two_dev):
        """ZeRO-1 only re-places the optimizer update: same batch sharding,
        same all-reduced gradient, elementwise update math. Losses match
        DataParallel to the last float32 ULP or two (measured max diff
        2.4e-7 at step 10 — resharding changes XLA's fusion grouping, so
        strict bit equality is not a stable contract, ULP-level is)."""
        m = _model(dtpu.ZeroDataParallel(devices=two_dev))
        losses = _step_losses(m, dp_run["x"], dp_run["y"], steps=10)
        np.testing.assert_allclose(losses, dp_run["losses"],
                                   rtol=1e-6, atol=1e-7)

    def test_memory_telemetry_shows_the_win(self, dp_run, two_dev):
        """fit telemetry reports measured per-device model-state bytes;
        on Adam, ZeRO-1 over 2 devices must cut them (3x params -> 2x)."""
        m = _model(dtpu.ZeroDataParallel(devices=two_dev))
        _step_losses(m, dp_run["x"], dp_run["y"], steps=1)
        mine = m.last_fit_telemetry["model_state_bytes_per_device"]
        ref = dp_run["telemetry"]["model_state_bytes_per_device"]
        assert mine < ref * 0.75, (mine, ref)
        # allocator stats are backend-dependent; the key must exist (None
        # on XLA:CPU, a peak-bytes dict on HBM backends)
        assert "device_memory" in m.last_fit_telemetry


class TestFSDPOverData:
    def test_params_and_opt_sharded_over_data(self, two_dev):
        m = _model(dtpu.FSDP(devices=two_dev))
        m.build((28, 28, 1))
        k = m.params["dense"]["kernel"]
        assert k.sharding.spec == PartitionSpec("data", None)
        mu = m.opt_state.inner_state[0].mu["dense"]["kernel"]
        assert mu.sharding.spec == PartitionSpec("data", None)

    def test_matches_dp(self, dp_run, two_dev):
        # Param-sharded matmuls may legitimately regroup reductions
        # (contraction-dim shards psum partial products), so the contract
        # is float-tight, not bitwise.
        m = _model(dtpu.FSDP(devices=two_dev))
        losses = _step_losses(m, dp_run["x"], dp_run["y"], steps=10)
        np.testing.assert_allclose(losses, dp_run["losses"],
                                   rtol=2e-5, atol=2e-6)


class TestGradAccum:
    def test_matches_equivalent_big_batch(self, dp_run, two_dev):
        """fit(grad_accum=4) at batch 32 == one 32-row batch per step: the
        same rows, the same mean gradient (f32-accumulated), one optimizer
        update. Losses match the big-batch run to the last ULP or two
        (the cross-microbatch mean regroups one f32 reduction; measured
        max diff 2.4e-7 over 10 steps)."""
        m = _model(dtpu.DataParallel(devices=two_dev))
        losses = _step_losses(m, dp_run["x"], dp_run["y"], steps=10,
                              grad_accum=4)
        np.testing.assert_allclose(losses, dp_run["losses"],
                                   rtol=1e-6, atol=1e-7)

    def test_composes_with_steps_per_execution(self, dp_run, two_dev):
        """K=2 fused dispatch x M=2 accumulation: one [K*M, micro, ...]
        staging, K optimizer steps per dispatch, same losses."""
        m = _model(dtpu.DataParallel(devices=two_dev),
                   steps_per_execution=2)
        h = m.fit(dp_run["x"], dp_run["y"], batch_size=32, epochs=1,
                  steps_per_epoch=10, verbose=0, seed=5, shuffle=False,
                  grad_accum=2)
        ref = float(np.mean(dp_run["losses"]))
        assert abs(h.history["loss"][0] - ref) < 1e-6
        assert m.step == 10  # optimizer steps, not microbatches

    def test_composes_with_zero1(self, dp_run, two_dev):
        m = _model(dtpu.ZeroDataParallel(devices=two_dev))
        losses = _step_losses(m, dp_run["x"], dp_run["y"], steps=3,
                              grad_accum=2)
        np.testing.assert_array_equal(losses, dp_run["losses"][:3])

    def test_validation(self, two_dev):
        x, y = _data(64)
        m = _model(dtpu.DataParallel(devices=two_dev))
        with pytest.raises(ValueError, match="grad_accum"):
            m.fit(x, y, batch_size=32, epochs=1, steps_per_epoch=1,
                  verbose=0, grad_accum=0)
        with pytest.raises(ValueError, match="divide"):
            m.fit(x, y, batch_size=32, epochs=1, steps_per_epoch=1,
                  verbose=0, grad_accum=5)


class TestCheckpointUnderSharding:
    def test_zero1_resumes_with_live_learning_rate(self, two_dev, tmp_path):
        """Regression for the inject_hyperparams round-trip under sharded
        optimizer state: a ZeRO-1 run whose LR was changed at runtime must
        resume with THAT learning rate (not the compile-time one), with
        the moments coming back data-sharded."""
        x, y = _data(64)
        m = _model(dtpu.ZeroDataParallel(devices=two_dev))
        m.fit(x, y, batch_size=32, epochs=1, steps_per_epoch=2, verbose=0,
              seed=0)
        m.set_learning_rate(3.3e-4)
        ck = dtpu.Checkpointer(tmp_path)
        ck.save(m)

        m2 = _model(dtpu.ZeroDataParallel(devices=two_dev))
        assert ck.restore_into(m2) == 2
        assert abs(m2.get_learning_rate() - 3.3e-4) < 1e-9
        mu = m2.opt_state.inner_state[0].mu["dense"]["kernel"]
        assert mu.sharding.spec == PartitionSpec("data", None)
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(
                m.opt_state.inner_state[0].mu["dense"]["kernel"])),
            np.asarray(jax.device_get(mu)),
        )
        # and training continues from the restored state
        m2.fit(x, y, batch_size=32, epochs=1, steps_per_epoch=1, verbose=0,
               seed=0)
        assert m2.step == 3

    def test_restore_across_strategy_change(self, two_dev, tmp_path):
        """A checkpoint is strategy-portable: save under replicated DP,
        restore into FSDP (and back) — values identical, placement the
        LIVE strategy's."""
        x, y = _data(64)
        m = _model(dtpu.DataParallel(devices=two_dev))
        m.fit(x, y, batch_size=32, epochs=1, steps_per_epoch=2, verbose=0,
              seed=0)
        ck = dtpu.Checkpointer(tmp_path)
        ck.save(m)

        m2 = _model(dtpu.FSDP(devices=two_dev))
        ck.restore_into(m2)
        assert m2.params["dense"]["kernel"].sharding.spec == \
            PartitionSpec("data", None)
        e1 = m.evaluate(x, y, batch_size=32, verbose=0)
        e2 = m2.evaluate(x, y, batch_size=32, verbose=0)
        assert abs(e1["loss"] - e2["loss"]) < 1e-6

    def test_sharded_checkpointer_roundtrips_zero1(self, two_dev, tmp_path):
        """ShardedCheckpointer writes each unique shard block once and
        rebuilds under the live sharding — including ZeRO-1's data-sharded
        moments and the replicated hyperparams."""
        x, y = _data(64)
        m = _model(dtpu.ZeroDataParallel(devices=two_dev))
        m.fit(x, y, batch_size=32, epochs=1, steps_per_epoch=2, verbose=0,
              seed=0)
        m.set_learning_rate(7e-4)
        sk = dtpu.ShardedCheckpointer(tmp_path)
        sk.save(m)
        m2 = _model(dtpu.ZeroDataParallel(devices=two_dev))
        assert sk.restore_into(m2) == 2
        assert abs(m2.get_learning_rate() - 7e-4) < 1e-9
        mu = m2.opt_state.inner_state[0].mu["dense"]["kernel"]
        assert mu.sharding.spec == PartitionSpec("data", None)
